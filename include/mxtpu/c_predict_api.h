/*
 * C predict ABI — standalone inference entry points.
 *
 * Mirrors the reference's include/mxnet/c_predict_api.h:78-207 surface.
 * Link against libmxtpu_predict.so (built by src/capi/Makefile) or load
 * it with dlopen/ctypes.  The library embeds the Python/XLA runtime; the
 * ABI below is plain C.
 */
#ifndef MXTPU_C_PREDICT_API_H_
#define MXTPU_C_PREDICT_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* PredictorHandle;
typedef void* NDListHandle;
typedef uint32_t mx_uint;
typedef float mx_float;

/* Returns the last error message from any failed call (thread-local). */
const char* MXGetLastError(void);

/* Create a predictor from symbol JSON + serialized params.
 * dev_type: 1 = cpu, 2 = tpu.  Input shapes are given CSR-style:
 * shape of input i is input_shape_data[indptr[i]..indptr[i+1]).  */
int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char** input_keys,
                 const mx_uint* input_shape_indptr,
                 const mx_uint* input_shape_data, PredictorHandle* out);

int MXPredSetInput(PredictorHandle handle, const char* key,
                   const mx_float* data, mx_uint size);
int MXPredForward(PredictorHandle handle);
int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint** shape_data, mx_uint* shape_ndim);
int MXPredGetOutput(PredictorHandle handle, mx_uint index, mx_float* data,
                    mx_uint size);
int MXPredFree(PredictorHandle handle);

/* NDArray-file list access (param inspection without a predictor). */
int MXNDListCreate(const char* nd_file_bytes, int nd_file_size,
                   NDListHandle* out, mx_uint* out_length);
int MXNDListGet(NDListHandle handle, mx_uint index, const char** out_key,
                const mx_float** out_data, const mx_uint** out_shape,
                mx_uint* out_ndim);
int MXNDListFree(NDListHandle handle);

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_C_PREDICT_API_H_ */
