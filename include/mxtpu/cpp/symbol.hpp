// C++ convenience binding over the symbol/executor C ABI
// (include/mxtpu/c_api.h) — the analogue of the reference
// cpp-package's Symbol/Executor (cpp-package/include/mxnet-cpp/
// symbol.h, executor.h), scoped to the graph-training surface:
// load a serialized symbol, SimpleBind, Forward/Backward, and the
// caller drives parameter updates through Op("sgd_update") on the
// aliased argument arrays.
//
// Header-only; link against libmxtpu_nd.so.
#ifndef MXTPU_CPP_SYMBOL_HPP_
#define MXTPU_CPP_SYMBOL_HPP_

#include <map>
#include <string>
#include <utility>
#include <vector>

#include "ndarray.hpp"

namespace mxtpu {

class Symbol {
 public:
  explicit Symbol(const std::string& json) {
    Check(MXSymbolCreateFromJSON(json.c_str(), &handle_));
  }
  Symbol(Symbol&& o) noexcept : handle_(o.handle_) { o.handle_ = nullptr; }
  Symbol(const Symbol&) = delete;
  Symbol& operator=(const Symbol&) = delete;
  ~Symbol() {
    if (handle_) MXSymbolFree(handle_);
  }

  SymbolHandle handle() const { return handle_; }

  std::vector<std::string> ListArguments() const {
    const char* s = nullptr;
    Check(MXSymbolListArguments(handle_, &s));
    return SplitLines(s);
  }
  std::vector<std::string> ListAuxiliaryStates() const {
    const char* s = nullptr;
    Check(MXSymbolListAuxiliaryStates(handle_, &s));
    return SplitLines(s);
  }
  std::vector<std::string> ListOutputs() const {
    const char* s = nullptr;
    Check(MXSymbolListOutputs(handle_, &s));
    return SplitLines(s);
  }
  std::string ToJSON() const {
    const char* s = nullptr;
    Check(MXSymbolSaveToJSON(handle_, &s));
    return s;
  }

 private:
  SymbolHandle handle_ = nullptr;
};

// A bound computation: owns the executor handle plus the argument/
// gradient/aux arrays it aliases.  Args()/Grads() expose them by name;
// mutating an arg (sgd_update through the op ABI's donation path) is
// visible to the next Forward, and Backward fills the grad arrays.
class Executor {
 public:
  Executor(const Symbol& sym,
           const std::map<std::string, std::vector<mx_uint>>& input_shapes,
           const std::string& grad_req = "write", int dev_type = 1,
           int dev_id = 0) {
    std::vector<const char*> keys;
    std::vector<mx_uint> flat;
    std::vector<mx_uint> ndims;
    for (auto& kv : input_shapes) {
      keys.push_back(kv.first.c_str());
      ndims.push_back(static_cast<mx_uint>(kv.second.size()));
      for (mx_uint d : kv.second) flat.push_back(d);
    }
    mx_uint n_args = 0, n_aux = 0;
    NDArrayHandle *args = nullptr, *grads = nullptr, *aux = nullptr;
    Check(MXExecutorSimpleBind(
        sym.handle(), dev_type, dev_id, grad_req.c_str(),
        static_cast<mx_uint>(keys.size()), keys.data(), flat.data(),
        ndims.data(), &handle_, &n_args, &args, &grads, &n_aux, &aux));
    arg_names_ = sym.ListArguments();
    aux_names_ = sym.ListAuxiliaryStates();
    for (mx_uint i = 0; i < n_args; ++i) {
      args_.emplace(arg_names_[i], NDArray::Adopt(args[i]));
      if (grads[i])
        grads_.emplace(arg_names_[i], NDArray::Adopt(grads[i]));
    }
    for (mx_uint i = 0; i < n_aux; ++i)
      aux_.emplace(aux_names_[i], NDArray::Adopt(aux[i]));
  }
  Executor(const Executor&) = delete;
  Executor& operator=(const Executor&) = delete;
  ~Executor() {
    if (handle_) MXExecutorFree(handle_);
  }

  void Forward(bool is_train) {
    Check(MXExecutorForward(handle_, is_train ? 1 : 0));
  }

  // loss-head graphs (SoftmaxOutput etc.) take no explicit head grads
  void Backward() { Check(MXExecutorBackward(handle_, 0, nullptr)); }

  std::vector<NDArray> Outputs() {
    mx_uint n = 0;
    NDArrayHandle* outs = nullptr;
    Check(MXExecutorOutputs(handle_, &n, &outs));
    std::vector<NDArray> result;
    result.reserve(n);
    for (mx_uint i = 0; i < n; ++i)
      result.push_back(NDArray::Adopt(outs[i]));
    return result;
  }

  std::map<std::string, NDArray>& Args() { return args_; }
  std::map<std::string, NDArray>& Grads() { return grads_; }
  std::map<std::string, NDArray>& Aux() { return aux_; }
  const std::vector<std::string>& ArgNames() const { return arg_names_; }

 private:
  ExecutorHandle handle_ = nullptr;
  std::vector<std::string> arg_names_;
  std::vector<std::string> aux_names_;
  std::map<std::string, NDArray> args_;
  std::map<std::string, NDArray> grads_;
  std::map<std::string, NDArray> aux_;
};

}  // namespace mxtpu

#endif  // MXTPU_CPP_SYMBOL_HPP_
