// C++ convenience binding over the NDArray C ABI
// (include/mxtpu/c_api.h) — the analogue of the reference's
// cpp-package (cpp-package/include/mxnet-cpp/ndarray.h: NDArray RAII +
// Operator invocation), hand-written instead of generated because the
// C surface here is one generic MXImperativeInvoke rather than
// per-op C entry points.
//
// Header-only; link against libmxtpu_nd.so.  Exceptions carry
// MXGetLastError.
#ifndef MXTPU_CPP_NDARRAY_HPP_
#define MXTPU_CPP_NDARRAY_HPP_

#include <cstring>
#include <iomanip>
#include <limits>
#include <map>
#include <sstream>
#include <stdexcept>
#include <string>
#include <type_traits>
#include <utility>
#include <vector>

#include "../c_api.h"

namespace mxtpu {

inline void Check(int rc) {
  if (rc != 0) throw std::runtime_error(MXGetLastError());
}

// Owned device array.  Copyable handles are deliberately NOT provided:
// the C handles are unique owners, so NDArray is move-only (like
// std::unique_ptr), and Clone() makes an explicit device copy.
class NDArray {
 public:
  NDArray() : handle_(nullptr) {}

  explicit NDArray(const std::vector<mx_uint>& shape,
                   int dtype = MXTPU_DTYPE_FLOAT32) {
    Check(MXNDArrayCreate(shape.data(),
                          static_cast<mx_uint>(shape.size()), 1, 0, 0,
                          dtype, &handle_));
  }

  NDArray(const std::vector<mx_uint>& shape,
          const std::vector<float>& values)
      : NDArray(shape) {
    CopyFrom(values.data(), values.size() * sizeof(float));
  }

  // adopt an ABI-owned handle (e.g. an MXImperativeInvoke output)
  static NDArray Adopt(NDArrayHandle h) {
    NDArray a;
    a.handle_ = h;
    return a;
  }

  NDArray(NDArray&& other) noexcept : handle_(other.handle_) {
    other.handle_ = nullptr;
  }
  NDArray& operator=(NDArray&& other) noexcept {
    if (this != &other) {
      Release();
      handle_ = other.handle_;
      other.handle_ = nullptr;
    }
    return *this;
  }
  NDArray(const NDArray&) = delete;
  NDArray& operator=(const NDArray&) = delete;
  ~NDArray() { Release(); }

  NDArrayHandle handle() const { return handle_; }

  std::vector<mx_uint> Shape() const {
    mx_uint dim = 0;
    const mx_uint* data = nullptr;
    Check(MXNDArrayGetShape(handle_, &dim, &data));
    return std::vector<mx_uint>(data, data + dim);
  }

  size_t Size() const {
    size_t n = 1;
    for (mx_uint d : Shape()) n *= d;
    return n;
  }

  int DType() const {
    int dt = 0;
    Check(MXNDArrayGetDType(handle_, &dt));
    return dt;
  }

  void CopyFrom(const void* data, size_t nbytes) {
    Check(MXNDArraySyncCopyFromCPU(handle_, data, nbytes));
  }

  std::vector<float> ToVector() const {
    std::vector<float> out(Size());
    Check(MXNDArraySyncCopyToCPU(handle_, out.data(),
                                 out.size() * sizeof(float)));
    return out;
  }

  NDArray Clone() const;

 private:
  void Release() {
    if (handle_) MXNDArrayFree(handle_);
    handle_ = nullptr;
  }
  NDArrayHandle handle_;
};

// One operator invocation (reference: mxnet-cpp Operator chaining API).
//   auto outs = Op("sgd_update").Arg(w).Arg(g)
//                  .Set("lr", 0.1f).Invoke();
class Op {
 public:
  explicit Op(std::string name) : name_(std::move(name)) {}

  Op& Arg(const NDArray& a) {
    inputs_.push_back(a.handle());
    return *this;
  }

  template <typename T>
  Op& Set(const std::string& key, const T& value) {
    std::ostringstream ss;
    // if constexpr: the discarded branch must not instantiate
    // numeric_limits<char[N]> for string-literal params
    if constexpr (std::is_floating_point<std::decay_t<T>>::value) {
      // round-trip precision: default 6-digit formatting would
      // silently alter hyper-parameters (e.g. adam epsilon) in transit
      ss << std::setprecision(
          std::numeric_limits<std::decay_t<T>>::max_digits10);
    }
    ss << value;
    params_.emplace_back(key, ss.str());
    return *this;
  }

  std::vector<NDArray> Invoke() {
    std::vector<const char*> keys, vals;
    for (auto& kv : params_) {
      keys.push_back(kv.first.c_str());
      vals.push_back(kv.second.c_str());
    }
    int num_out = 0;
    NDArrayHandle* outs = nullptr;
    Check(MXImperativeInvoke(
        name_.c_str(), static_cast<int>(inputs_.size()), inputs_.data(),
        &num_out, &outs, static_cast<int>(params_.size()),
        keys.empty() ? nullptr : keys.data(),
        vals.empty() ? nullptr : vals.data()));
    std::vector<NDArray> result;
    result.reserve(num_out);
    for (int i = 0; i < num_out; ++i)
      result.push_back(NDArray::Adopt(outs[i]));
    return result;
  }

 private:
  std::string name_;
  std::vector<NDArrayHandle> inputs_;
  std::vector<std::pair<std::string, std::string>> params_;
};

inline NDArray NDArray::Clone() const {
  Op op("_copy");
  op.Arg(*this);
  auto outs = op.Invoke();
  return std::move(outs[0]);
}

// split the ABI's newline-joined listing convention
inline std::vector<std::string> SplitLines(const char* joined) {
  std::vector<std::string> out;
  std::string cur;
  for (const char* p = joined;; ++p) {
    if (*p == '\n' || *p == '\0') {
      if (!cur.empty()) out.push_back(cur);
      cur.clear();
      if (*p == '\0') break;
    } else {
      cur += *p;
    }
  }
  return out;
}

inline std::vector<std::string> ListOps() {
  const char* joined = nullptr;
  Check(MXListAllOpNames(&joined));
  return SplitLines(joined);
}

inline void Save(const std::string& fname,
                 const std::map<std::string, const NDArray*>& arrays) {
  std::vector<NDArrayHandle> handles;
  std::vector<const char*> keys;
  for (auto& kv : arrays) {
    keys.push_back(kv.first.c_str());
    handles.push_back(kv.second->handle());
  }
  Check(MXNDArraySave(fname.c_str(),
                      static_cast<mx_uint>(handles.size()),
                      handles.data(), keys.data()));
}

inline std::map<std::string, NDArray> Load(const std::string& fname) {
  mx_uint n = 0, n_names = 0;
  NDArrayHandle* arrs = nullptr;
  const char** names = nullptr;
  Check(MXNDArrayLoad(fname.c_str(), &n, &arrs, &n_names, &names));
  std::map<std::string, NDArray> out;
  for (mx_uint i = 0; i < n; ++i) {
    std::string key = (n_names && names[i]) ? names[i]
                                            : std::to_string(i);
    out.emplace(key, NDArray::Adopt(arrs[i]));
  }
  return out;
}

}  // namespace mxtpu

#endif  // MXTPU_CPP_NDARRAY_HPP_
