/*
 * NDArray + operator-invoke C ABI for the TPU-native framework.
 *
 * Mirrors the core training surface of the reference's
 * include/mxnet/c_api.h: array lifecycle (MXNDArrayCreate/Free),
 * host<->device copies (MXNDArraySyncCopyFromCPU/ToCPU), shape/dtype
 * introspection, the generic operator entry point MXImperativeInvoke
 * (every registered operator — including the fused optimizer updates,
 * so full training loops are reachable from C), registry listing, and
 * save/load of the framework-native checkpoint container (reference
 * API shape; byte layout per ndarray/utils.py, not the CUDA-era
 * reference binary).
 *
 * Like the predict ABI (c_predict_api.h), the library embeds CPython
 * and routes to mxnet_tpu.capi_bridge; only raw buffers, ints and
 * strings cross this boundary, so any FFI can bind it.
 *
 * All functions return 0 on success, -1 on error (MXGetLastError).
 */
#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* NDArrayHandle;
typedef uint32_t mx_uint;

/* dtype enum (reference mshadow type flags; 7 extends with bfloat16) */
#define MXTPU_DTYPE_FLOAT32 0
#define MXTPU_DTYPE_FLOAT64 1
#define MXTPU_DTYPE_FLOAT16 2
#define MXTPU_DTYPE_UINT8 3
#define MXTPU_DTYPE_INT32 4
#define MXTPU_DTYPE_INT8 5
#define MXTPU_DTYPE_INT64 6
#define MXTPU_DTYPE_BFLOAT16 7

const char* MXGetLastError(void);
int MXGetVersion(int* out);

/* -- lifecycle -------------------------------------------------------- */
int MXNDArrayCreate(const mx_uint* shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, int dtype,
                    NDArrayHandle* out);
int MXNDArrayFree(NDArrayHandle handle);

/* -- host<->device copies (buffer bytes are the array's dtype) -------- */
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t size_bytes);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data,
                           size_t size_bytes);

/* -- introspection ---------------------------------------------------- */
int MXNDArrayGetShape(NDArrayHandle handle, mx_uint* out_dim,
                      const mx_uint** out_pdata);
int MXNDArrayGetDType(NDArrayHandle handle, int* out_dtype);

/* -- operators -------------------------------------------------------- */
/* invoke a registered operator by name; outputs are NEW handles the
 * caller frees.  params are string key/value pairs exactly like the
 * reference's MXImperativeInvoke. *num_outputs is set on return and
 * *outputs points at an array valid until the next invoke on any
 * thread-local handle (copy the handles out immediately). */
int MXImperativeInvoke(const char* op_name, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, int num_params,
                       const char** param_keys,
                       const char** param_vals);
/* newline-joined registry listing; pointer valid until next call */
int MXListAllOpNames(const char** out_names);

/* -- save/load (framework-native container, reference API shape) ----- */
int MXNDArraySave(const char* fname, mx_uint num_args,
                  NDArrayHandle* args, const char** keys);
/* loads into library-owned arrays; *out_names entries may be NULL for
 * unnamed saves.  Handles are new and caller-freed; the name/handle
 * arrays stay valid until the next MXNDArrayLoad. */
int MXNDArrayLoad(const char* fname, mx_uint* out_size,
                  NDArrayHandle** out_arr, mx_uint* out_name_size,
                  const char*** out_names);

#ifdef __cplusplus
}
#endif

#endif /* MXTPU_C_API_H_ */
