/*
 * NDArray + operator-invoke C ABI for the TPU-native framework.
 *
 * Mirrors the core training surface of the reference's
 * include/mxnet/c_api.h: array lifecycle (MXNDArrayCreate/Free),
 * host<->device copies (MXNDArraySyncCopyFromCPU/ToCPU), shape/dtype
 * introspection, the generic operator entry point MXImperativeInvoke
 * (every registered operator — including the fused optimizer updates,
 * so full training loops are reachable from C), registry listing, and
 * save/load of the framework-native checkpoint container (reference
 * API shape; byte layout per ndarray/utils.py, not the CUDA-era
 * reference binary).
 *
 * Like the predict ABI (c_predict_api.h), the library embeds CPython
 * and routes to mxnet_tpu.capi_bridge; only raw buffers, ints and
 * strings cross this boundary, so any FFI can bind it.
 *
 * All functions return 0 on success, -1 on error (MXGetLastError).
 */
#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* NDArrayHandle;
typedef uint32_t mx_uint;

/* dtype enum (reference mshadow type flags; 7 extends with bfloat16) */
#define MXTPU_DTYPE_FLOAT32 0
#define MXTPU_DTYPE_FLOAT64 1
#define MXTPU_DTYPE_FLOAT16 2
#define MXTPU_DTYPE_UINT8 3
#define MXTPU_DTYPE_INT32 4
#define MXTPU_DTYPE_INT8 5
#define MXTPU_DTYPE_INT64 6
#define MXTPU_DTYPE_BFLOAT16 7

const char* MXGetLastError(void);
int MXGetVersion(int* out);

/* -- lifecycle -------------------------------------------------------- */
int MXNDArrayCreate(const mx_uint* shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, int dtype,
                    NDArrayHandle* out);
int MXNDArrayFree(NDArrayHandle handle);

/* -- host<->device copies (buffer bytes are the array's dtype) -------- */
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t size_bytes);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data,
                           size_t size_bytes);

/* -- introspection ---------------------------------------------------- */
int MXNDArrayGetShape(NDArrayHandle handle, mx_uint* out_dim,
                      const mx_uint** out_pdata);
int MXNDArrayGetDType(NDArrayHandle handle, int* out_dtype);

/* -- operators -------------------------------------------------------- */
/* invoke a registered operator by name; outputs are NEW handles the
 * caller frees.  params are string key/value pairs exactly like the
 * reference's MXImperativeInvoke. *num_outputs is set on return and
 * *outputs points at an array valid until the next invoke on any
 * thread-local handle (copy the handles out immediately). */
int MXImperativeInvoke(const char* op_name, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, int num_params,
                       const char** param_keys,
                       const char** param_vals);
/* newline-joined registry listing; pointer valid until next call */
int MXListAllOpNames(const char** out_names);

/* -- save/load (framework-native container, reference API shape) ----- */
int MXNDArraySave(const char* fname, mx_uint num_args,
                  NDArrayHandle* args, const char** keys);
/* loads into library-owned arrays; *out_names entries may be NULL for
 * unnamed saves.  Handles are new and caller-freed; the name/handle
 * arrays stay valid until the next MXNDArrayLoad. */
int MXNDArrayLoad(const char* fname, mx_uint* out_size,
                  NDArrayHandle** out_arr, mx_uint* out_name_size,
                  const char*** out_names);

/* -- symbol + executor (reference: c_api_symbolic.cc, c_api_executor.cc) */
typedef void* SymbolHandle;
typedef void* ExecutorHandle;

int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out);
int MXSymbolFree(SymbolHandle handle);
/* serialized graph; pointer valid until the next SaveToJSON */
int MXSymbolSaveToJSON(SymbolHandle handle, const char** out_json);
/* newline-joined name listings; pointer valid until the next listing */
int MXSymbolListArguments(SymbolHandle handle, const char** out);
int MXSymbolListAuxiliaryStates(SymbolHandle handle, const char** out);
int MXSymbolListOutputs(SymbolHandle handle, const char** out);

/* Bind with named input shapes (flat shape_data, per-input ndim);
 * remaining shapes are inferred and allocated on the device.
 * in_args/arg_grads/aux_states receive one NEW caller-owned handle per
 * name in listing order; arg_grads entries are NULL where grad_req
 * excludes the argument.  The handle arrays stay valid until the next
 * SimpleBind on the thread.  The handles alias the executor state:
 * writing an argument (e.g. an sgd_update step through
 * MXImperativeInvoke) is seen by the next Forward, and Backward writes
 * gradients into the arg_grads arrays. */
int MXExecutorSimpleBind(SymbolHandle sym, int dev_type, int dev_id,
                         const char* grad_req, mx_uint num_inputs,
                         const char** input_keys,
                         const mx_uint* input_shape_data,
                         const mx_uint* input_shape_ndim,
                         ExecutorHandle* out, mx_uint* num_in_args,
                         NDArrayHandle** in_args,
                         NDArrayHandle** arg_grads, mx_uint* num_aux,
                         NDArrayHandle** aux_states);
int MXExecutorFree(ExecutorHandle handle);
int MXExecutorForward(ExecutorHandle handle, int is_train);
/* head_grads may be empty (len 0) for loss-style single outputs */
int MXExecutorBackward(ExecutorHandle handle, mx_uint len,
                       NDArrayHandle* head_grads);
/* NEW caller-owned output handles; array valid until next call */
int MXExecutorOutputs(ExecutorHandle handle, mx_uint* out_size,
                      NDArrayHandle** outputs);

/* -- kvstore (reference: c_api.cc MXKVStore* string-key variants) ---- */
typedef void* KVStoreHandle;

int MXKVStoreCreate(const char* type, KVStoreHandle* out);
int MXKVStoreFree(KVStoreHandle handle);
int MXKVStoreInitEx(KVStoreHandle handle, mx_uint num, const char** keys,
                    NDArrayHandle* vals);
int MXKVStorePushEx(KVStoreHandle handle, mx_uint num, const char** keys,
                    NDArrayHandle* vals, int priority);
int MXKVStorePullEx(KVStoreHandle handle, mx_uint num, const char** keys,
                    NDArrayHandle* outs, int priority);
/* pointer valid until the next GetType call */
int MXKVStoreGetType(KVStoreHandle handle, const char** out);
int MXKVStoreGetRank(KVStoreHandle handle, int* out);
int MXKVStoreGetGroupSize(KVStoreHandle handle, int* out);

/* -- data iterators (reference: c_api.cc MXDataIter*) ----------------- */
typedef void* DataIterHandle;

/* newline-joined creator listing; pointer valid until next call */
int MXListDataIters(const char** out_names);
/* create by name with string params (e.g. MNISTIter, image/label path
 * + batch_size); Get* read the batch the last Next advanced to, as NEW
 * caller-owned NDArray handles */
int MXDataIterCreateIter(const char* name, mx_uint num_params,
                         const char** keys, const char** vals,
                         DataIterHandle* out);
int MXDataIterFree(DataIterHandle handle);
int MXDataIterNext(DataIterHandle handle, int* out);
int MXDataIterBeforeFirst(DataIterHandle handle);
int MXDataIterGetData(DataIterHandle handle, NDArrayHandle* out);
int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle* out);
int MXDataIterGetPadNum(DataIterHandle handle, int* out);

#ifdef __cplusplus
}
#endif

#endif /* MXTPU_C_API_H_ */
