"""BaseModule — the high-level train/score/predict loop.

Reference: ``python/mxnet/module/base_module.py`` (1,074 LoC; fit:410 runs
epochs of forward_backward/update/update_metric with callbacks and
checkpointing).
"""

from __future__ import annotations

import logging
import time

from .. import metric as metric_mod
from .. import ndarray as nd
from ..model import BatchEndParam
from ..base import MXNetError
from .._kvstore_impl import EvictedWorkerError

__all__ = ["BaseModule"]


def _as_list(obj):
    if obj is None:
        return []
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # -- abstract interface ------------------------------------------------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError

    @property
    def symbol(self):
        return self._symbol

    # -- composite ops -----------------------------------------------------
    def forward_backward(self, data_batch):
        """(reference: base_module.py forward_backward:194)"""
        self.forward(data_batch, is_train=True)
        self.backward()

    def forward_backward_update(self, data_batch):
        """One full training step.  Subclasses may override to fuse the
        three stages into fewer device dispatches (Module folds them
        into a single donated XLA program — see module.py)."""
        self.forward_backward(data_batch)
        self.update()

    def _fire(self, callbacks, param):
        for cb in _as_list(callbacks):
            cb(param)

    def _eval_batches(self, eval_data, num_batch, reset):
        """Inference-mode batches with a LAZY padding-trimmed outputs
        getter (score never asks for outputs, so none are fetched)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for idx, batch in enumerate(eval_data):
            if idx == num_batch:
                return
            self.forward(batch, is_train=False)
            keep = -(batch.pad or 0) or None
            yield idx, batch, \
                lambda k=keep: [o[:k] for o in self.get_outputs()]

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        """(reference: base_module.py score:210)"""
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        seen = 0
        for idx, batch, _ in self._eval_batches(eval_data, num_batch,
                                                reset):
            self.update_metric(eval_metric, batch.label)
            seen = idx + 1
            self._fire(batch_end_callback, BatchEndParam(
                epoch=epoch, nbatch=idx, eval_metric=eval_metric,
                locals=locals()))
        if score_end_callback:
            self._fire(score_end_callback, BatchEndParam(
                epoch=epoch, nbatch=seen, eval_metric=eval_metric,
                locals=locals()))
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        for idx, batch, outs in self._eval_batches(eval_data, num_batch,
                                                   reset):
            yield outs(), idx, batch

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False,
                sparse_row_id_fn=None):
        """(reference: base_module.py predict:320)"""
        collected = [
            [o.copy() for o in outs()]
            for _, _, outs in self._eval_batches(eval_data, num_batch,
                                                 reset)]
        if not collected or not merge_batches:
            return collected
        widths = {len(outs) for outs in collected}
        assert len(widths) == 1, \
            "Cannot merge batches, as num of outputs is not the " \
            "same in mini-batches. Maybe bucketing is used?"
        merged = [nd.concatenate(list(column))
                  for column in zip(*collected)]
        if len(merged) == 1 and not always_output_list:
            return merged[0]
        return merged

    def _capture_job_state(self, epoch, nbatch, eval_metric, train_data):
        """Assemble the resumable TrainJobState for a checkpoint taken
        at a batch boundary (``nbatch`` = last completed batch; ``-1``
        = epoch boundary, data/metric start fresh next epoch)."""
        from ..resilience.jobstate import TrainJobState
        frag = self.job_state() if hasattr(self, "job_state") else {}
        metric_st = data_st = None
        if nbatch >= 0:
            sd = getattr(eval_metric, "state_dict", None)
            metric_st = sd() if sd is not None else None
            sd = getattr(train_data, "state_dict", None)
            data_st = sd() if sd is not None else None
        return TrainJobState(epoch=epoch, nbatch=nbatch, module=frag,
                             metric=metric_st, data=data_st)

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None,
            checkpoint_manager=None, resume_from=None,
            checkpoint_every_n_batches=None, device_prefetch=None):
        """Full training loop (reference: base_module.py fit:410).

        ``device_prefetch=K`` (or the ``MXNET_DEVICE_PREFETCH`` env
        knob) wraps *train_data* in a
        :class:`~mxnet_tpu.io.DevicePrefetcher`: host decode and the
        host→device transfer run on a background thread into a ring of
        K device-resident batches, so the fused step never waits on
        input (see docs/perf_input_pipeline.md).  Job-state capture
        and mid-epoch resume go THROUGH the wrapper — checkpoint and
        resume with the same wrapping, or the restored data-pipeline
        state will name the wrong iterator type.

        With a :class:`~mxnet_tpu.resilience.CheckpointManager`, each
        epoch end writes a crash-safe checkpoint through it, and a
        preemption request (``resilience.request_preemption()``, an
        installed SIGTERM handler, or the chaos harness) is honored at
        the next batch boundary: the in-flight batch finishes, a
        checkpoint is committed, and fit returns cleanly — the job
        resumes from ``checkpoint_manager.restore_latest()``.

        Job-level fault tolerance (see docs/resilience.md):

        * every checkpoint carries a
          :class:`~mxnet_tpu.resilience.TrainJobState` — epoch/batch
          cursor, PRNG + update counts, guard counters, metric and
          data-pipeline position;
        * ``resume_from`` (a ``CheckpointRecord``, or ``"latest"`` to
          take ``checkpoint_manager.restore_latest()``) restores ALL
          of it and continues **mid-epoch, bit-exactly**: no batch is
          replayed or skipped, dropout masks and metric values match
          the uninterrupted run;
        * ``checkpoint_every_n_batches=N`` additionally commits a
          full resumable checkpoint every N batches, bounding the
          work a kill at ANY step can lose;
        * each batch boundary ticks the supervisor heartbeat
          (``resilience.supervisor``) so a hung step is distinguishable
          from a dead process.
        """
        assert num_epoch is not None, "please specify number of epochs"
        from ..io.device_prefetch import maybe_wrap
        ctxs = getattr(self, "_context", None)
        train_data, created_prefetcher = maybe_wrap(
            train_data, device_prefetch,
            device=ctxs[0] if ctxs else None)
        try:
            return self._fit_loop(
                train_data, eval_data, eval_metric, epoch_end_callback,
                batch_end_callback, kvstore, optimizer, optimizer_params,
                eval_end_callback, eval_batch_end_callback, initializer,
                arg_params, aux_params, allow_missing, force_rebind,
                force_init, begin_epoch, num_epoch, validation_metric,
                monitor, checkpoint_manager, resume_from,
                checkpoint_every_n_batches)
        finally:
            if created_prefetcher:
                # release the ring (depth x batch bytes of device
                # memory) and its producer thread with the loop
                train_data.close()

    def _fit_loop(self, train_data, eval_data, eval_metric,
                  epoch_end_callback, batch_end_callback, kvstore,
                  optimizer, optimizer_params, eval_end_callback,
                  eval_batch_end_callback, initializer, arg_params,
                  aux_params, allow_missing, force_rebind, force_init,
                  begin_epoch, num_epoch, validation_metric, monitor,
                  checkpoint_manager, resume_from,
                  checkpoint_every_n_batches):
        from .. import initializer as init_mod
        from .. import resilience
        from ..resilience import supervisor as _sup
        if initializer is None:
            initializer = init_mod.Uniform(0.01)

        job = None
        record = None
        if resume_from is not None:
            record = resume_from
            if record in (True, "latest"):
                assert checkpoint_manager is not None, \
                    "resume_from='latest' needs a checkpoint_manager"
                record = checkpoint_manager.restore_latest()
            if record is not None:
                _, arg_params, aux_params = record.load()
                job = record.load_job_state()
                if job is None:
                    # params-only checkpoint (pre-job-state, or a raw
                    # save_module): the record's epoch completed —
                    # resume at the NEXT epoch, never re-train epoch 0
                    # over the restored weights
                    begin_epoch = max(begin_epoch, record.epoch + 1)
                self.logger.info(
                    "resuming from checkpoint epoch %d (%s)",
                    record.epoch,
                    "mid-epoch job state" if job is not None
                    else "params only; starting at epoch %d"
                    % begin_epoch)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init or record is not None)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if record is not None and record.states_path is not None and \
                self.optimizer_initialized:
            self.load_optimizer_states(record.states_path)

        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        resume_epoch = resume_nbatch = None
        if job is not None:
            if job.module and hasattr(self, "load_job_state"):
                self.load_job_state(job.module)
            resume_epoch, resume_nbatch = job.epoch, job.nbatch
            begin_epoch = max(begin_epoch, resume_epoch)
            if job.nbatch >= 0:
                if job.metric is not None and \
                        hasattr(eval_metric, "load_state"):
                    eval_metric.load_state(job.metric)
                if job.data is not None and \
                        hasattr(train_data, "load_state"):
                    train_data.load_state(job.data)
                else:
                    self.logger.warning(
                        "resume: the data-pipeline position cannot be "
                        "restored (%s) — the resumed epoch restarts "
                        "its iterator and batches may be replayed",
                        "checkpoint carries no iterator state"
                        if job.data is None else
                        "%s has no load_state"
                        % type(train_data).__name__)
            elif resume_epoch > 0:
                # epoch-boundary resume: the iterator starts the next
                # epoch fresh (mirrors the end-of-epoch reset below)
                train_data.reset()

        for epoch in range(begin_epoch, num_epoch):
            resumed_mid_epoch = (job is not None and
                                 epoch == resume_epoch and
                                 resume_nbatch is not None and
                                 resume_nbatch >= 0)
            epoch_start = time.perf_counter()
            if not resumed_mid_epoch:
                eval_metric.reset()
            nbatch_offset = resume_nbatch + 1 if resumed_mid_epoch else 0
            for nbatch, data_batch in enumerate(train_data,
                                                start=nbatch_offset):
                if monitor is not None:
                    monitor.tic()
                try:
                    self.forward_backward_update(data_batch)
                except EvictedWorkerError as exc:
                    # this rank contributed to a round that completed
                    # without it (evicted while partitioned/stalled):
                    # its gradient was rejected TYPED, never merged.
                    # Re-sync params from the store, refresh the
                    # membership view, and rejoin at this boundary —
                    # the batch's update is lost, training is not.
                    self.logger.warning(
                        "evicted from the sync round (%s); re-syncing "
                        "params and rejoining", exc)
                    refresh = getattr(self._kvstore,
                                      "refresh_membership", None) \
                        if getattr(self, "_kvstore", None) is not None \
                        else None
                    if refresh is not None:
                        refresh()
                    resync = getattr(self, "resync_from_kvstore", None)
                    if resync is not None:
                        resync()
                    tick = getattr(self, "elastic_tick", None)
                    if tick is not None and not tick(train_data):
                        self.logger.warning(
                            "rank no longer a member after re-sync; "
                            "exiting fit cleanly")
                        return
                    continue
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                tick = getattr(self, "elastic_tick", None)
                if tick is not None and not tick(train_data):
                    # membership resize retired this rank: finish at
                    # the batch boundary and return cleanly (the
                    # survivors re-sharded the remaining epoch)
                    self.logger.warning(
                        "rank retired by an elastic resize at epoch %d "
                        "batch %d; exiting fit cleanly", epoch, nbatch)
                    return
                self._fire(batch_end_callback, BatchEndParam(
                    epoch=epoch, nbatch=nbatch,
                    eval_metric=eval_metric, locals=locals()))
                _sup.heartbeat()
                if checkpoint_every_n_batches and \
                        checkpoint_manager is not None and \
                        (nbatch + 1) % checkpoint_every_n_batches == 0:
                    checkpoint_manager.save_module(
                        self, epoch,
                        job_state=self._capture_job_state(
                            epoch, nbatch, eval_metric, train_data))
                if resilience.preemption_requested(tick=True):
                    # finish-the-batch semantics: the step and its
                    # callbacks completed; checkpoint and exit cleanly
                    from ..observability import events as _obs_events
                    _obs_events.emit(
                        "preempt", epoch=epoch, batch=nbatch,
                        checkpointing=checkpoint_manager is not None)
                    self.logger.warning(
                        "preemption requested: checkpointing after "
                        "epoch %d batch %d and exiting fit", epoch,
                        nbatch)
                    if checkpoint_manager is not None:
                        checkpoint_manager.save_module(
                            self, epoch,
                            job_state=self._capture_job_state(
                                epoch, nbatch, eval_metric, train_data))
                        checkpoint_manager.wait()
                    # consume the request: a later fit() in this
                    # process (in-process resume) must actually train
                    resilience.clear_preemption()
                    return

            # epoch boundary: settle any deferred async-guard
            # readbacks so divergence actions and counters never
            # cross an epoch (MXNET_GUARD_READBACK_LAG)
            drain = getattr(self, "drain_guard_readbacks", None)
            if drain is not None:
                drain()

            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.perf_counter() - epoch_start)

            # sync the user-visible snapshot, then checkpoint callbacks
            snapshot = self.get_params()
            self.set_params(*snapshot)
            for cb in _as_list(epoch_end_callback):
                cb(epoch, self.symbol, *snapshot)
            if checkpoint_manager is not None:
                checkpoint_manager.save_module(
                    self, epoch,
                    job_state=self._capture_job_state(
                        epoch + 1, -1, eval_metric, train_data))

            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)
            train_data.reset()

    # -- params ------------------------------------------------------------
    def get_params(self):
        raise NotImplementedError

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def install_monitor(self, mon):
        raise NotImplementedError

    @property
    def data_names(self):
        raise NotImplementedError

    @property
    def output_names(self):
        raise NotImplementedError

    @property
    def data_shapes(self):
        raise NotImplementedError

    @property
    def label_shapes(self):
        raise NotImplementedError

    @property
    def output_shapes(self):
        raise NotImplementedError
