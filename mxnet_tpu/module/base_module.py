"""BaseModule — the high-level train/score/predict loop.

Reference: ``python/mxnet/module/base_module.py`` (1,074 LoC; fit:410 runs
epochs of forward_backward/update/update_metric with callbacks and
checkpointing).
"""

from __future__ import annotations

import logging
import time

from .. import metric as metric_mod
from .. import ndarray as nd
from ..model import BatchEndParam
from ..base import MXNetError

__all__ = ["BaseModule"]


def _as_list(obj):
    if obj is None:
        return []
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # -- abstract interface ------------------------------------------------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError

    @property
    def symbol(self):
        return self._symbol

    # -- composite ops -----------------------------------------------------
    def forward_backward(self, data_batch):
        """(reference: base_module.py forward_backward:194)"""
        self.forward(data_batch, is_train=True)
        self.backward()

    def forward_backward_update(self, data_batch):
        """One full training step.  Subclasses may override to fuse the
        three stages into fewer device dispatches (Module folds them
        into a single donated XLA program — see module.py)."""
        self.forward_backward(data_batch)
        self.update()

    def _fire(self, callbacks, param):
        for cb in _as_list(callbacks):
            cb(param)

    def _eval_batches(self, eval_data, num_batch, reset):
        """Inference-mode batches with a LAZY padding-trimmed outputs
        getter (score never asks for outputs, so none are fetched)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for idx, batch in enumerate(eval_data):
            if idx == num_batch:
                return
            self.forward(batch, is_train=False)
            keep = -(batch.pad or 0) or None
            yield idx, batch, \
                lambda k=keep: [o[:k] for o in self.get_outputs()]

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        """(reference: base_module.py score:210)"""
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        seen = 0
        for idx, batch, _ in self._eval_batches(eval_data, num_batch,
                                                reset):
            self.update_metric(eval_metric, batch.label)
            seen = idx + 1
            self._fire(batch_end_callback, BatchEndParam(
                epoch=epoch, nbatch=idx, eval_metric=eval_metric,
                locals=locals()))
        if score_end_callback:
            self._fire(score_end_callback, BatchEndParam(
                epoch=epoch, nbatch=seen, eval_metric=eval_metric,
                locals=locals()))
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        for idx, batch, outs in self._eval_batches(eval_data, num_batch,
                                                   reset):
            yield outs(), idx, batch

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False,
                sparse_row_id_fn=None):
        """(reference: base_module.py predict:320)"""
        collected = [
            [o.copy() for o in outs()]
            for _, _, outs in self._eval_batches(eval_data, num_batch,
                                                 reset)]
        if not collected or not merge_batches:
            return collected
        widths = {len(outs) for outs in collected}
        assert len(widths) == 1, \
            "Cannot merge batches, as num of outputs is not the " \
            "same in mini-batches. Maybe bucketing is used?"
        merged = [nd.concatenate(list(column))
                  for column in zip(*collected)]
        if len(merged) == 1 and not always_output_list:
            return merged[0]
        return merged

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None,
            checkpoint_manager=None):
        """Full training loop (reference: base_module.py fit:410).

        With a :class:`~mxnet_tpu.resilience.CheckpointManager`, each
        epoch end writes a crash-safe checkpoint through it, and a
        preemption request (``resilience.request_preemption()``, an
        installed SIGTERM handler, or the chaos harness) is honored at
        the next batch boundary: the in-flight batch finishes, a
        checkpoint is committed, and fit returns cleanly — the job
        resumes from ``checkpoint_manager.restore_latest()``."""
        assert num_epoch is not None, "please specify number of epochs"
        from .. import initializer as init_mod
        from .. import resilience
        if initializer is None:
            initializer = init_mod.Uniform(0.01)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            epoch_start = time.perf_counter()
            eval_metric.reset()
            for nbatch, data_batch in enumerate(train_data):
                if monitor is not None:
                    monitor.tic()
                self.forward_backward_update(data_batch)
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                self._fire(batch_end_callback, BatchEndParam(
                    epoch=epoch, nbatch=nbatch,
                    eval_metric=eval_metric, locals=locals()))
                if resilience.preemption_requested(tick=True):
                    # finish-the-batch semantics: the step and its
                    # callbacks completed; checkpoint and exit cleanly
                    from ..observability import events as _obs_events
                    _obs_events.emit(
                        "preempt", epoch=epoch, batch=nbatch,
                        checkpointing=checkpoint_manager is not None)
                    self.logger.warning(
                        "preemption requested: checkpointing after "
                        "epoch %d batch %d and exiting fit", epoch,
                        nbatch)
                    if checkpoint_manager is not None:
                        checkpoint_manager.save_module(self, epoch)
                        checkpoint_manager.wait()
                    # consume the request: a later fit() in this
                    # process (in-process resume) must actually train
                    resilience.clear_preemption()
                    return

            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.perf_counter() - epoch_start)

            # sync the user-visible snapshot, then checkpoint callbacks
            snapshot = self.get_params()
            self.set_params(*snapshot)
            for cb in _as_list(epoch_end_callback):
                cb(epoch, self.symbol, *snapshot)
            if checkpoint_manager is not None:
                checkpoint_manager.save_module(self, epoch)

            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)
            train_data.reset()

    # -- params ------------------------------------------------------------
    def get_params(self):
        raise NotImplementedError

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def install_monitor(self, mon):
        raise NotImplementedError

    @property
    def data_names(self):
        raise NotImplementedError

    @property
    def output_names(self):
        raise NotImplementedError

    @property
    def data_shapes(self):
        raise NotImplementedError

    @property
    def label_shapes(self):
        raise NotImplementedError

    @property
    def output_shapes(self):
        raise NotImplementedError
