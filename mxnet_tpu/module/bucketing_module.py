"""BucketingModule — per-sequence-length executors sharing parameters.

Reference: ``python/mxnet/module/bucketing_module.py`` (543 LoC).

TPU-native mapping: each bucket key compiles to its own whole-graph XLA
executor (one static-shape program per sequence length — the recompile-
storm mitigation of SURVEY.md §7 hard part (e)); all bucket executors
share the SAME parameter NDArrays via shared_exec binding, so an update
through any bucket is immediately visible to all others, and the
optimizer/updater is created once and borrowed by every bucket module.
"""

from __future__ import annotations

import logging

from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    """(reference: bucketing_module.py BucketingModule:40)"""

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None,
                 group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._group2ctxs = group2ctxs
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._grad_req = "write"
        self._monitor = None

    def _reset_bind(self):
        self.binded = False
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        _, data_names, _ = self._call_sym_gen(self._default_bucket_key)
        return data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        symbol, _, _ = self._call_sym_gen(self._default_bucket_key)
        return symbol.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module._symbol

    def _call_sym_gen(self, bucket_key):
        out = self._sym_gen(bucket_key)
        if isinstance(out, tuple):
            return out
        return out, ("data",), ("softmax_label",)

    def get_params(self):
        assert self.params_initialized
        # all buckets share the default bucket's parameter arrays
        return self._buckets[self._default_bucket_key].get_params()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        assert self.binded, "call bind before set_params"
        if self.params_initialized and not force_init:
            self.logger.warning(
                "Parameters already initialized and force_init=False; "
                "set_params call ignored")
            return
        default_mod = self._buckets[self._default_bucket_key]
        if not allow_missing:
            have = set(arg_params or {})
            missing = [n for n in default_mod._exec_group.param_names
                       if n not in have]
            if missing:
                raise RuntimeError(
                    "set_params missing parameters %s and allow_missing "
                    "is False" % missing)
        default_mod._set_exec_params(arg_params, aux_params)
        self.params_initialized = True

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        self._buckets[self._default_bucket_key].init_params(
            initializer=initializer, arg_params=arg_params,
            aux_params=aux_params, allow_missing=allow_missing,
            force_init=force_init, allow_extra=allow_extra)
        self.params_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        assert shared_module is None, \
            "shared_module for BucketingModule is not supported"
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        symbol, data_names, label_names = self._call_sym_gen(
            self._default_bucket_key)
        module = Module(symbol, data_names=data_names,
                        label_names=label_names, logger=self.logger,
                        context=self._context,
                        fixed_param_names=self._fixed_param_names,
                        group2ctxs=self._group2ctxs)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False,
                    grad_req=grad_req)
        self._buckets[self._default_bucket_key] = module
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Bind (or reuse) the executor for *bucket_key*
        (reference: bucketing_module.py switch_bucket:406)."""
        assert self.binded, "call bind before switching bucket"
        if bucket_key not in self._buckets:
            symbol, data_names, label_names = self._call_sym_gen(
                bucket_key)
            default_mod = self._buckets[self._default_bucket_key]
            module = Module(symbol, data_names=data_names,
                            label_names=label_names, logger=self.logger,
                            context=self._context,
                            fixed_param_names=self._fixed_param_names,
                            group2ctxs=self._group2ctxs)
            # share parameter NDArrays with the default bucket
            module.bind(data_shapes, label_shapes, self.for_training,
                        self.inputs_need_grad,
                        shared_module=default_mod,
                        grad_req=self._grad_req)
            # borrow the optimizer/updater (reference:
            # module.borrow_optimizer) so update() uses ONE state store
            if default_mod.optimizer_initialized:
                self._borrow_optimizer(module, default_mod)
            if self._monitor is not None:
                module.install_monitor(self._monitor)
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    @staticmethod
    def _borrow_optimizer(module, shared_module):
        module._optimizer = shared_module._optimizer
        module._updater = shared_module._updater
        module._kvstore = shared_module._kvstore
        module._update_on_kvstore = shared_module._update_on_kvstore
        module.optimizer_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        default_mod = self._buckets[self._default_bucket_key]
        default_mod.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                   optimizer_params=optimizer_params,
                                   force_init=force_init)
        for key, mod in self._buckets.items():
            if key != self._default_bucket_key:
                self._borrow_optimizer(mod, default_mod)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key,
                           data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def forward_backward(self, data_batch):
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key,
                           data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward_backward(data_batch)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._curr_module.backward(out_grads)

    def update(self):
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        assert self.binded and self.params_initialized
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)

    def install_monitor(self, mon):
        assert self.binded
        self._monitor = mon
        for mod in self._buckets.values():
            mod.install_monitor(mon)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        checkpoint_manager=None):
        self._buckets[self._default_bucket_key].save_checkpoint(
            prefix, epoch, save_optimizer_states,
            checkpoint_manager=checkpoint_manager)

    def _optimizer_states_bytes(self):
        # CheckpointManager.save_module probes this (shared optimizer:
        # any bucket's module serializes the same updater state)
        return self._buckets[
            self._default_bucket_key]._optimizer_states_bytes()