"""Module — symbolic training on one or more devices.

Reference: ``python/mxnet/module/module.py`` (868 LoC) +
``executor_group.py`` (DataParallelExecutorGroup:143 — per-device executor
shards with gradient slicing).

TPU-native: each context gets one whole-graph XLA executor (see
mxnet_tpu/executor.py); the batch is sliced across contexts
(data-parallel), gradients are reduced to the update device, and the fused
``forward_backward`` path keeps each step a single compiled program per
device.  With ``kvstore='tpu'`` (mxnet_tpu/kvstore.py) the reduction runs
in-graph over the mesh instead of through this group.
"""

from __future__ import annotations

import logging

import numpy as _np

from .base_module import BaseModule, _as_list
from ..base import MXNetError
from ..context import Context, cpu, current_context
from .. import ndarray as nd
from ..ndarray import NDArray
from .. import optimizer as opt
from ..initializer import InitDesc
from ..model import load_checkpoint
from ..observability import metrics as _obs_metrics

__all__ = ["Module"]

# module-level instrument refs: these observe every train step, so
# they must not pay a registry lookup per dispatch (same discipline as
# the asnumpy counters in ndarray.py)
_FUSED_STEP_SECONDS = _obs_metrics.histogram(
    "fused_step_dispatch_seconds",
    "host-side latency of one full-fused train-step dispatch")
_TREE_APPLY_SECONDS = _obs_metrics.histogram(
    "tree_apply_dispatch_seconds",
    "host-side latency of one partial-fused tree-update dispatch")


class _ExecGroup:
    """Minimal DataParallelExecutorGroup (reference:
    executor_group.py:143)."""

    def __init__(self, symbol, contexts, data_names, label_names,
                 data_shapes, label_shapes, grad_req, fixed_param_names,
                 inputs_need_grad, shared_group=None, group2ctxs=None):
        self.symbol = symbol
        self.contexts = contexts
        self.data_names = list(data_names)
        self.label_names = list(label_names or [])
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.param_names = [n for n in self.arg_names
                            if n not in self.data_names and
                            n not in self.label_names]
        n_dev = len(contexts)
        self.batch_size = data_shapes[0][1][0]
        assert self.batch_size % n_dev == 0, \
            "batch size %d cannot be evenly split across %d devices" % (
                self.batch_size, n_dev)
        self.slice_size = self.batch_size // n_dev

        reqs = {}
        for name in self.arg_names:
            if name in self.data_names:
                reqs[name] = "write" if inputs_need_grad else "null"
            elif name in self.label_names:
                reqs[name] = "null"
            elif fixed_param_names and name in fixed_param_names:
                reqs[name] = "null"
            else:
                reqs[name] = grad_req
        self.grad_req = reqs

        self.execs = []
        for i, ctx in enumerate(contexts):
            shapes = {}
            for name, shape in data_shapes:
                shapes[name] = (self.slice_size,) + tuple(shape[1:])
            for name, shape in (label_shapes or []):
                shapes[name] = (self.slice_size,) + tuple(shape[1:])
            shared = shared_group.execs[i] if shared_group else None
            g2c = None
            if group2ctxs:
                # per-device group maps (reference: group2ctxs is a list
                # of dicts, one per data-parallel context)
                g2c = group2ctxs[i] if isinstance(group2ctxs, list) \
                    else group2ctxs
            ex = symbol.simple_bind(ctx=ctx, grad_req=reqs,
                                    shared_exec=shared, group2ctx=g2c,
                                    **shapes)
            self.execs.append(ex)

    def _slices(self, arrs):
        out = []
        for i in range(len(self.contexts)):
            lo = i * self.slice_size
            hi = lo + self.slice_size
            out.append([a[lo:hi] if a.shape[0] == self.batch_size else a
                        for a in arrs])
        return out

    def forward(self, data_batch, is_train=False):
        data = _as_list(data_batch.data)
        labels = _as_list(data_batch.label) if data_batch.label else []
        data_slices = self._slices(data)
        label_slices = self._slices(labels) if labels else \
            [[] for _ in self.contexts]
        for ex, dslc, lslc in zip(self.execs, data_slices, label_slices):
            kwargs = {}
            for name, arr in zip(self.data_names, dslc):
                kwargs[name] = arr
            for name, arr in zip(self.label_names, lslc):
                if name in ex.arg_dict:
                    kwargs[name] = arr
            ex.forward(is_train=is_train, **kwargs)

    def forward_backward(self, data_batch):
        data = _as_list(data_batch.data)
        labels = _as_list(data_batch.label) if data_batch.label else []
        data_slices = self._slices(data)
        label_slices = self._slices(labels) if labels else \
            [[] for _ in self.contexts]
        for ex, dslc, lslc in zip(self.execs, data_slices, label_slices):
            kwargs = {}
            for name, arr in zip(self.data_names, dslc):
                kwargs[name] = arr
            for name, arr in zip(self.label_names, lslc):
                if name in ex.arg_dict:
                    kwargs[name] = arr
            ex.forward_backward(**kwargs)

    def backward(self, out_grads=None):
        for ex in self.execs:
            ex.backward(out_grads)

    def get_outputs(self, merge_multi_context=True):
        if len(self.execs) == 1:
            return list(self.execs[0].outputs)
        if not merge_multi_context:
            return [list(ex.outputs) for ex in self.execs]
        merged = []
        for i in range(len(self.execs[0].outputs)):
            merged.append(nd.concatenate(
                [ex.outputs[i].as_in_context(self.contexts[0])
                 for ex in self.execs], axis=0))
        return merged

    def reduce_grads(self):
        """Sum gradients across device replicas into exec 0
        (reference: kvstore local push/pull)."""
        if len(self.execs) == 1:
            return
        from ..ndarray.sparse import BaseSparseNDArray
        for name in self.param_names:
            if self.grad_req[name] == "null":
                continue
            total = self.execs[0].grad_dict[name]
            if isinstance(total, BaseSparseNDArray):
                # rsp grads (Embedding sparse_grad): sparse_add
                # concatenates shards (duplicate row ids), so
                # re-canonicalize to unique sorted rows — the row-wise
                # lazy optimizer kernels require duplicate-free ids —
                # and give each exec its OWN container (a shared one
                # would make the next backwards clobber each other)
                from ..ops.sparse_graph import dedup_rsp_pairs
                summed = total
                for ex in self.execs[1:]:
                    summed = summed + ex.grad_dict[name]
                ids, vals = dedup_rsp_pairs(summed.indices._data,
                                            summed.data._data,
                                            summed.shape[0])
                # mutate each exec's OWN bind-time container in place:
                # args_grad / C-ABI handles stay aliased
                for ex in self.execs:
                    dst = ex.grad_dict[name]
                    dst._data = vals
                    dst._aux[0] = ids
                continue
            for ex in self.execs[1:]:
                total._data = (total + ex.grad_dict[name].as_in_context(
                    self.contexts[0]))._data
            for ex in self.execs[1:]:
                total.as_in_context(
                    ex.grad_dict[name].context).copyto(ex.grad_dict[name])

    def broadcast_params(self):
        for name in self.param_names:
            src = self.execs[0].arg_dict[name]
            for ex in self.execs[1:]:
                src.copyto(ex.arg_dict[name])
        for name in self.aux_names:
            src = self.execs[0].aux_dict[name]
            for ex in self.execs[1:]:
                src.copyto(ex.aux_dict[name])


class Module(BaseModule):
    """(reference: module.py Module:60)"""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None,
                 group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        if context is None:
            context = current_context()
        if isinstance(context, Context):
            context = [context]
        self._context = context
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        self._group2ctxs = group2ctxs
        self._exec_group = None
        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._optimizer = None
        self._updater = None
        self._kvstore = None
        self._grad_req = "write"
        self._monitor = None
        # fused train step (forward_backward_update): lazy-built context
        # dict, False once setup found a hard blocker, None = not built
        self._fused = None
        # device-resident optimizer state tree; None = (re)import from
        # the legacy Updater before the next fused step
        self._fused_state = None
        # non-finite guard (resilience subsystem): explicit config from
        # set_nonfinite_guard, None = fall back to the env knobs
        self._guard = None
        self._guard_skipped = 0     # total skipped steps
        self._guard_consec = 0      # consecutive skipped steps
        # async guard accounting (MXNET_GUARD_READBACK_LAG): deferred
        # skipped-flag device scalars, resolved FIFO with bounded lag
        # so the host never blocks on step N's readback before
        # dispatching step N+1 (full-fused path only — the partial
        # path needs the flag synchronously for its host-side aux
        # restore).  See docs/perf_input_pipeline.md.
        import collections
        self._guard_pending = collections.deque()
        self._step_seq = 0          # forward_backward_update calls
        #                             (chaos nan-injection index)
        self._forward_pad = 0       # rows the last inference forward
        #                             zero-padded (remainder fix-up)

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        checkpoint_manager=None):
        """Checkpoint through the resilience subsystem: every file is
        written atomically (tmp + fsync + rename) and committed to the
        checksum manifest LAST, so a preemption at any instruction
        leaves the previous checkpoint fully restorable (see
        docs/resilience.md).  File names match the reference layout."""
        from ..resilience.checkpoint import CheckpointManager
        mgr = checkpoint_manager or CheckpointManager(prefix)
        states = None
        if save_optimizer_states:
            states = self._optimizer_states_bytes()
        arg_params, aux_params = self.get_params()
        mgr.save_checkpoint(epoch, symbol=self._symbol,
                            arg_params=arg_params, aux_params=aux_params,
                            optimizer_states=states)

    def _optimizer_states_bytes(self):
        """Optimizer state serialized in the legacy per-index Updater
        format — fused-trained state is exported into the Updater
        first, so the bytes are identical whichever path trained it."""
        assert self.optimizer_initialized
        if self._updater is not None:
            self._sync_fused_to_updater()
            return self._updater.get_states()
        if self._kvstore is not None and self._update_on_kvstore:
            return self._kvstore.get_optimizer_states()
        return None

    def save_optimizer_states(self, fname):
        """Serialize optimizer state (atomic write; legacy Updater
        format — see :meth:`_optimizer_states_bytes`)."""
        from ..resilience.checkpoint import atomic_write
        states = self._optimizer_states_bytes()
        if states is not None:
            atomic_write(fname, states)

    def load_optimizer_states(self, fname):
        """Load optimizer state saved by :meth:`save_optimizer_states`;
        the fused path re-imports it on its next step.

        The blob is validated against the CURRENT optimizer (class +
        baked hyper-param signature) before it is applied: a stale or
        foreign file raises a typed
        :class:`~mxnet_tpu.resilience.StateMismatchError` instead of
        silently training with the wrong momenta after a resume
        (``MXNET_OPTSTATE_MISMATCH=reinit`` downgrades to
        warn-and-reinit)."""
        assert self.optimizer_initialized
        if self._updater is not None:
            with open(fname, "rb") as f:
                blob = f.read()
            if self._apply_updater_states(blob):
                self._fused_state = None
        elif self._kvstore is not None and self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)

    def _apply_updater_states(self, blob):
        """Validate + apply an optimizer-state blob to the local
        Updater; False = mismatched and re-initialized instead."""
        import pickle
        try:
            # parse ONCE: validation reads the header, set_states the
            # payload — a large model's momenta must not deserialize
            # twice per resume
            blob = pickle.loads(blob)
        except Exception as exc:
            # keep the raw bytes: states_mismatch re-attempts the load
            # and reports the blob as unreadable with its own reason
            self.logger.debug("optimizer-state blob pre-parse failed "
                              "(%s: %s); deferring to validation",
                              type(exc).__name__, exc)
        reason = opt.states_mismatch(blob, self._optimizer)
        if reason:
            from ..config import get_env
            from ..resilience import StateMismatchError
            if get_env("MXNET_OPTSTATE_MISMATCH").lower() == "reinit":
                self.logger.warning(
                    "optimizer state blob does not match the current "
                    "optimizer (%s); re-initializing optimizer state "
                    "fresh (MXNET_OPTSTATE_MISMATCH=reinit)", reason)
                self._updater.states.clear()
                self._updater.states_synced.clear()
                self._fused_state = None
                return False
            raise StateMismatchError(
                "refusing to load optimizer state: %s (set "
                "MXNET_OPTSTATE_MISMATCH=reinit to warn and start "
                "from fresh state instead)" % reason)
        self._updater.set_states(blob)
        return True

    # -- job state (mid-epoch bit-exact resume) ----------------------------
    def job_state(self):
        """The module's resumable non-parameter fragment for
        :class:`~mxnet_tpu.resilience.TrainJobState`:

        Deferred guard readbacks are drained first — the guard
        counters captured here must cover every step already
        dispatched, or a resumed job would forget skipped steps whose
        readbacks were still in flight.

        * ``step_seq`` — the global forward_backward_update count
          (chaos step indexing, guard event stamps);
        * guard counters (``guard_skipped`` / ``guard_consec``) so a
          restart does not forget how close the job was to its
          divergence limit;
        * the executor's PRNG base key, and
        * the optimizer's per-index update counts — the fused step's
          in-graph ``fold_in(key, step)`` makes RNG resume exact
          precisely iff BOTH of those are restored (``.states`` blobs
          carry momenta, not counts)."""
        assert self.binded
        self.drain_guard_readbacks()
        frag = {"step_seq": self._step_seq,
                "guard_skipped": self._guard_skipped,
                "guard_consec": self._guard_consec,
                "rng": self._exec_group.execs[0].rng_state()}
        if self._optimizer is not None:
            frag["opt_counts"] = dict(self._optimizer._index_update_count)
            frag["num_update"] = int(self._optimizer.num_update)
            frag["begin_num_update"] = \
                int(self._optimizer.begin_num_update)
        return frag

    def load_job_state(self, frag):
        """Restore a :meth:`job_state` fragment (after bind +
        init_optimizer; pairs with ``load_optimizer_states``)."""
        assert self.binded
        self._step_seq = int(frag.get("step_seq", 0))
        self._guard_skipped = int(frag.get("guard_skipped", 0))
        self._guard_consec = int(frag.get("guard_consec", 0))
        rng = frag.get("rng")
        if rng is not None:
            # every exec starts from the same constructed key, so the
            # restored key rebinds them all identically
            for ex in self._exec_group.execs:
                ex.set_rng_state(rng)
        if self._optimizer is not None and "opt_counts" in frag:
            self._optimizer._index_update_count = {
                int(k): int(v) for k, v in frag["opt_counts"].items()}
            self._optimizer.num_update = int(
                frag.get("num_update", self._optimizer.num_update))
            self._optimizer.begin_num_update = int(
                frag.get("begin_num_update",
                         self._optimizer.begin_num_update))

    # -- properties --------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        outs = self._exec_group.get_outputs()
        return list(zip(self.output_names, [o.shape for o in outs]))

    # -- binding -----------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req

        def _norm(shapes):
            if shapes is None:
                return None
            out = []
            for s in shapes:
                if hasattr(s, "name"):
                    out.append((s.name, tuple(s.shape)))
                else:
                    out.append((s[0], tuple(s[1])))
            return out

        self._data_shapes = _norm(data_shapes)
        self._label_shapes = _norm(label_shapes)
        shared_group = shared_module._exec_group if shared_module is not \
            None else None
        self._exec_group = _ExecGroup(
            self._symbol, self._context, self._data_names,
            self._label_names, self._data_shapes, self._label_shapes,
            grad_req if for_training else "null",
            self._fixed_param_names, inputs_need_grad,
            shared_group=shared_group,
            group2ctxs=self._group2ctxs)
        if shared_module is not None and shared_module.params_initialized:
            # only inherit initialization when EVERY parameter was
            # actually aliased from the shared executors (a shape
            # mismatch leaves a fresh zero array that must not be
            # mistaken for an initialized weight)
            all_shared = all(
                ex.arg_dict[n] is sx.arg_dict[n]
                for ex, sx in zip(self._exec_group.execs,
                                  shared_group.execs)
                for n in self._exec_group.param_names
                if n in sx.arg_dict)
            # every param must also exist in the shared module
            all_present = all(
                n in shared_group.execs[0].arg_dict
                for n in self._exec_group.param_names)
            if all_shared and all_present:
                self.params_initialized = True
            else:
                self.logger.warning(
                    "shared_module bind: not all parameters could be "
                    "aliased (shape mismatch or missing) — call "
                    "init_params on this module")
        # a rebind voids any fused-step program built on the old
        # executors (but NOT the state tree: it re-exports via the
        # updater interop if the caller kept the same optimizer)
        self._sync_fused_to_updater()
        self._fused = None
        self._fused_state = None
        self.binded = True
        if self._arg_params is not None:
            self._set_exec_params(self._arg_params, self._aux_params)

    # -- parameters --------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        from .. import initializer as init_mod
        if initializer is None:
            initializer = init_mod.Uniform(0.01)

        ex0 = self._exec_group.execs[0]
        for name in self._exec_group.param_names:
            arr = ex0.arg_dict[name]
            if arg_params is not None and name in arg_params:
                arg_params[name].copyto(arr)
            elif arg_params is not None and not allow_missing:
                raise RuntimeError(
                    "Parameter %r is missing from arg_params and "
                    "allow_missing is False" % name)
            else:
                initializer(InitDesc(name), arr)
        for name in self._exec_group.aux_names:
            arr = ex0.aux_dict[name]
            if aux_params is not None and name in aux_params:
                aux_params[name].copyto(arr)
            else:
                initializer(InitDesc(name), arr)
        self._exec_group.broadcast_params()
        self.params_initialized = True
        self._params_dirty = False

    def _set_exec_params(self, arg_params, aux_params):
        ex0 = self._exec_group.execs[0]
        for name, arr in (arg_params or {}).items():
            if name in ex0.arg_dict:
                arr.copyto(ex0.arg_dict[name])
        for name, arr in (aux_params or {}).items():
            if name in ex0.aux_dict:
                arr.copyto(ex0.aux_dict[name])
        self._exec_group.broadcast_params()
        self.params_initialized = True

    def get_params(self):
        assert self.binded and self.params_initialized
        ex0 = self._exec_group.execs[0]
        arg_params = {n: ex0.arg_dict[n].copy()
                      for n in self._exec_group.param_names}
        aux_params = {n: ex0.aux_dict[n].copy()
                      for n in self._exec_group.aux_names}
        return arg_params, aux_params

    # -- optimizer ---------------------------------------------------------
    @staticmethod
    def _create_kvstore(kvstore, num_device):
        """(reference: python/mxnet/model.py _create_kvstore) — returns
        (kv, update_on_kvstore).  A plain local/device store on a single
        device is pointless overhead, so it collapses to None."""
        import os
        from .._kvstore_impl import KVStoreBase
        from .. import kvstore as kvs
        if kvstore is None or kvstore == "":
            return None, False
        if isinstance(kvstore, KVStoreBase):
            kv = kvstore
        else:
            if num_device == 1 and "dist" not in kvstore:
                return None, False
            kv = kvs.create(kvstore)
        from ..config import get_env
        update_on_kvstore = get_env("MXNET_UPDATE_ON_KVSTORE")
        if "async" in getattr(kv, "type", ""):
            update_on_kvstore = True
        return kv, update_on_kvstore

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """(reference: module.py init_optimizer:333 — creates the kvstore,
        registers weights, and places the updater locally or server-side)"""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        # the fused step closes over the optimizer — rebuild lazily
        self._fused = None
        self._fused_state = None
        self._kvstore, self._update_on_kvstore = self._create_kvstore(
            kvstore, len(self._context))
        if isinstance(optimizer, str):
            batch_size = self._exec_group.batch_size
            if self._kvstore is not None and \
                    "dist" in getattr(self._kvstore, "type", ""):
                # reference module.py init_optimizer: dist servers sum
                # all workers' gradient sums, so the mean is over the
                # GLOBAL batch
                batch_size *= self._kvstore.num_workers
            idx2name = {i: n for i, n in
                        enumerate(self._exec_group.param_names)}
            optimizer_params = dict(optimizer_params)
            # reference module.py init_optimizer: grads are rescaled by
            # 1/batch_size unless the caller overrides
            optimizer_params.setdefault("rescale_grad", 1.0 / batch_size)
            optimizer = opt.create(optimizer, param_idx2name=idx2name,
                                   **optimizer_params)
        self._optimizer = optimizer
        if self._kvstore is not None:
            group = self._exec_group
            for i, name in enumerate(group.param_names):
                self._kvstore.init(i, group.execs[0].arg_dict[name])
                # all workers/devices start from the stored copy (rank
                # 0's weights) — reference model.py _initialize_kvstore
                # pulls right after init when update_on_kvstore
                self._kvstore.pull(
                    i, out=[ex.arg_dict[name] for ex in group.execs])
            if self._update_on_kvstore:
                self._kvstore.set_optimizer(self._optimizer)
        if self._kvstore is not None and self._update_on_kvstore:
            self._updater = None
        else:
            self._updater = opt.get_updater(optimizer)
        if getattr(self, "_preload_opt_states", None):
            if self._updater is not None:
                with open(self._preload_opt_states, "rb") as f:
                    self._apply_updater_states(f.read())
            else:
                # updater lives in the kvstore (update_on_kvstore);
                # reference routes this through
                # kvstore.load_optimizer_states (module.py:373)
                self._kvstore.load_optimizer_states(
                    self._preload_opt_states)
            self._preload_opt_states = None
        # elastic membership bookkeeping (dist stores only): the view
        # the optimizer hyper-state was last scaled for
        self._elastic_grad_scale = 1.0
        view = self._elastic_view()
        self._elastic_mep = view["mep"] if view else None
        self._elastic_active = (max(1, len(view["members"]))
                                if view else 1)
        self.optimizer_initialized = True

    # -- elastic membership (dist_sync; docs/resilience.md) ----------------
    # class-level defaults: elastic_tick() is safe to call before
    # init_optimizer has stamped the instance state
    _elastic_mep = None
    _elastic_active = 1
    _elastic_grad_scale = 1.0

    def _elastic_view(self):
        """The kvstore's live membership view, or None when this
        module is not training against an elastic (dist) store."""
        kv = getattr(self, "_kvstore", None)
        if kv is None or "dist" not in getattr(kv, "type", ""):
            return None
        mv = getattr(kv, "membership", None)
        return mv() if callable(mv) else None

    def resync_from_kvstore(self):
        """Pull current params from the store into every executor —
        the re-sync an evicted-then-rejoining worker must do before
        contributing again (the server rejects its stale pushes with
        a typed EvictedWorkerError until it does)."""
        assert self._kvstore is not None
        group = self._exec_group
        for i, name in enumerate(group.param_names):
            self._kvstore.pull(
                i, out=[ex.arg_dict[name] for ex in group.execs])
        self._params_dirty = True

    def elastic_tick(self, train_data=None):
        """Batch-boundary elasticity hook (called by ``fit``): notice
        a membership-epoch change and apply the whole transition at
        this boundary — re-shard *train_data* to this rank's slot,
        and rescale the gradient contribution for the new effective
        global batch (per-worker batch is fixed, so N→M workers moves
        the global batch by M/N).  The rescale goes through the
        optimizer's ``rescale_grad`` when the updater is local (a
        hyper mutation the fused step's hyper_sig rebuild picks up),
        or through a worker-side pre-scale of pushed gradients when
        the updater runs server-side.  Returns False when this rank
        is no longer a member (retired by a resize / evicted) — the
        caller should stop training cleanly."""
        view = self._elastic_view()
        if view is None or view["mep"] == self._elastic_mep:
            return True
        members = sorted(view["members"])
        active = max(1, len(members))
        old_active = self._elastic_active
        self._elastic_mep = view["mep"]
        self._elastic_active = active
        rank = self._kvstore.rank
        from ..observability import events as _obs_events
        if rank not in members:
            if rank < view.get("world", 0):
                # evicted but NOT resized away: re-admission is one
                # barrier (or one post-fence push) away — keep
                # training; the admission bumps the epoch again and
                # the next tick re-shards to this rank's slot
                # keep _elastic_active at its pre-eviction value: the
                # rescale factor must net out to 1 across the
                # evict→readmit round trip
                _obs_events.emit("membership",
                                 action="awaiting_readmission",
                                 rank=rank, mep=view["mep"],
                                 members=members)
                self._elastic_active = old_active
                return True
            _obs_events.emit("membership", action="retired", rank=rank,
                             mep=view["mep"], members=members)
            return False
        if active != old_active:
            factor = old_active / float(active)
            if self._updater is not None and \
                    getattr(self._optimizer, "rescale_grad", None) \
                    is not None:
                self._optimizer.rescale_grad *= factor
            else:
                self._elastic_grad_scale *= factor
        if train_data is not None:
            rp = getattr(train_data, "repartition", None)
            if rp is not None:
                rp(members.index(rank), active)
            else:
                logging.getLogger(__name__).warning(
                    "elastic membership changed (epoch %s, %d active) "
                    "but %s has no repartition() — the data pipeline "
                    "keeps its old sharding", view["mep"], active,
                    type(train_data).__name__)
        _obs_events.emit("membership", action="rescale", rank=rank,
                         mep=view["mep"], members=members,
                         old_active=old_active, active=active)
        return True

    # -- execution ---------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        self._forward_pad = 0
        if not is_train:
            data_batch = self._pad_remainder_batch(data_batch)
        self._exec_group.forward(data_batch, is_train)

    def _pad_remainder_batch(self, data_batch):
        """Inference remainder fix-up: a ragged last batch (fewer rows
        than the bound batch size) is zero-padded up to the bound
        shape — the nearest compiled bucket — and its outputs trimmed
        by :meth:`get_outputs`, instead of rebinding the executors to
        a fresh shape.  Without this, every distinct remainder size
        retraced and recompiled the whole inference program (the
        jit-churn hazard graftlint JG004 flags); with it a ragged
        epoch runs on exactly one compiled program (pinned by
        tests/test_module.py)."""
        data = _as_list(data_batch.data)
        if not data or not getattr(data[0], "shape", None):
            return data_batch
        n = data[0].shape[0]
        bs = self._exec_group.batch_size
        if n >= bs:
            return data_batch
        from ..io import DataBatch

        def _pad(arrs):
            out = []
            for a in arrs:
                a = a if isinstance(a, NDArray) else nd.array(a)
                filler = nd.zeros((bs - a.shape[0],) + tuple(a.shape[1:]),
                                  dtype=a.dtype)
                out.append(nd.concatenate([a, filler], axis=0))
            return out

        labels = _as_list(data_batch.label)
        self._forward_pad = bs - n
        return DataBatch(data=_pad(data),
                         label=_pad(labels) if labels else None,
                         pad=data_batch.pad, index=data_batch.index)

    def forward_backward(self, data_batch):
        """Fused per-device forward+backward (single XLA program each).

        A subclass overriding ``forward`` or ``backward`` (gradient
        hooks, custom heads) gets the composed two-stage path instead,
        so its override actually runs — the reference's
        base_module.py:194 semantics."""
        assert self.binded and self.params_initialized
        self._forward_pad = 0
        cls = type(self)
        if cls.forward is not Module.forward or \
                cls.backward is not Module.backward:
            self.forward(data_batch, is_train=True)
            self.backward()
            return
        self._exec_group.forward_backward(data_batch)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec_group.backward(out_grads)

    def update(self):
        """(reference: module.py update:644 — kvstore push/pull + updater)"""
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._params_dirty = True
        if self._fused_state is not None:
            # fused steps ran earlier: the device tree holds the truth —
            # hand it back to the Updater so this legacy sweep continues
            # from it (and the next fused step re-imports)
            self._sync_fused_to_updater()
            self._fused_state = None
        group = self._exec_group
        ex0 = group.execs[0]
        if self._kvstore is not None and self._update_on_kvstore:
            # push grads -> (server/store applies updater) -> pull weights
            scale = getattr(self, "_elastic_grad_scale", 1.0)
            for i, name in enumerate(group.param_names):
                if group.grad_req[name] == "null":
                    continue
                grads = [ex.grad_dict[name] for ex in group.execs]
                if scale != 1.0:
                    # elastic rescale for a server-side updater: the
                    # server's optimizer keeps its launch-time
                    # rescale_grad, so the effective-batch change of a
                    # resize is applied to the contribution itself
                    grads = [g * scale for g in grads]
                self._kvstore.push(i, grads)
            if "dist" in getattr(self._kvstore, "type", ""):
                self._kvstore.barrier()
            for i, name in enumerate(group.param_names):
                if group.grad_req[name] == "null":
                    continue
                self._kvstore.pull(
                    i, out=[ex.arg_dict[name] for ex in group.execs])
            return
        self._aggregate_grads(group)
        for i, name in enumerate(group.param_names):
            if group.grad_req[name] == "null":
                continue
            # grads were summed across device slices, so with
            # rescale_grad=1/batch_size this is already the batch mean
            self._updater(i, ex0.grad_dict[name], ex0.arg_dict[name])
        self._exec_group.broadcast_params()

    def _aggregate_grads(self, group):
        """Cross-device gradient aggregation into every exec's
        grad_dict: through a (local) kvstore when one is attached,
        otherwise an in-process reduce.  Shared by the legacy update()
        sweep and the partial-fused step."""
        if self._kvstore is not None:
            for i, name in enumerate(group.param_names):
                if group.grad_req[name] == "null":
                    continue
                self._kvstore.push(
                    i, [ex.grad_dict[name] for ex in group.execs])
                self._kvstore.pull(
                    i, out=[ex.grad_dict[name] for ex in group.execs])
        else:
            group.reduce_grads()

    # -- non-finite guard (resilience subsystem) ---------------------------
    def set_nonfinite_guard(self, enabled=True, max_consecutive=None,
                            action="raise", checkpoint_manager=None):
        """Configure the NaN/Inf divergence guard for this module's
        training steps.

        When enabled, a step whose loss/gradients contain non-finite
        values is SKIPPED: weights and optimizer state pass through
        bit-identical.  On the fused path the check is one in-graph
        ``isfinite`` reduction compiled into the same single XLA
        program (plus one scalar device→host read per step for the
        counter); the legacy/fallback path mirrors it host-side.

        *max_consecutive* bad steps in a row trigger the divergence
        *action*: ``"raise"`` (:class:`~mxnet_tpu.resilience.
        DivergenceError`), ``"rollback"`` (restore the newest intact
        checkpoint from *checkpoint_manager* — params and optimizer
        state), or any callable taking this module.  ``None`` means
        the ``MXNET_GUARD_MAX_BAD_STEPS`` env default (0 = skip and
        count only).  Explicit configuration overrides the
        ``MXNET_GUARD_NONFINITE`` env knob in both directions."""
        # reconfiguring must not orphan readbacks deferred under the
        # OLD config — account them against it first
        self.drain_guard_readbacks(_cfg=self._guard_cfg())
        if enabled:
            if max_consecutive is None:
                from ..config import get_env
                max_consecutive = get_env("MXNET_GUARD_MAX_BAD_STEPS")
            self._guard = {"enabled": True,
                           "max_consecutive": max_consecutive or 0,
                           "action": action,
                           "manager": checkpoint_manager}
        else:
            self._guard = {"enabled": False}
        self._guard_consec = 0
        # the guard is compiled into the fused program — rebuild lazily
        self._fused = None
        return self

    @property
    def nonfinite_skipped(self):
        """Total training steps the guard skipped for non-finite
        loss/gradients (drains any deferred readbacks first, so the
        count covers every step already dispatched)."""
        self.drain_guard_readbacks()
        return self._guard_skipped

    def _guard_lag(self):
        """Allowed guard-readback lag in steps (0 = synchronous)."""
        from ..config import get_env
        return max(0, get_env("MXNET_GUARD_READBACK_LAG"))

    def _account_guard(self, skipped_scalar, guard):
        """Account one full-fused step's guard flag: synchronously at
        lag 0, else parked in the FIFO and resolved once it is more
        than *lag* steps old — the host dispatches ahead while the
        device finishes, and divergence actions still fire within the
        documented lag bound (FIFO order preserves the consecutive-bad
        counting exactly)."""
        lag = self._guard_lag()
        if lag <= 0:
            # one scalar device->host read per step — the price of a
            # synchronous host-visible skip counter
            self._note_guard(int(skipped_scalar), guard)
            return
        # park the dispatch-time step with the scalar: events and
        # divergence actions must blame the step that DIVERGED, not
        # the later step whose dispatch resolved the readback
        self._guard_pending.append((skipped_scalar, self._step_seq))
        while len(self._guard_pending) > lag:
            scalar, step = self._guard_pending.popleft()
            self._note_guard(int(scalar), guard, step=step)

    def drain_guard_readbacks(self, _cfg=None):
        """Resolve every deferred guard readback NOW (blocks on the
        device).  Called at epoch end, on preemption, before job-state
        capture, and on guard reconfiguration — the points where the
        counters must be exact.  A pending divergence action fires
        here (FIFO, same counting as the synchronous path)."""
        if not self._guard_pending:
            return
        cfg = _cfg or self._guard_cfg()
        if cfg is None:
            # the guard was turned off (env knob flip) with readbacks
            # in flight: still count the skips, with no action armed
            cfg = {"enabled": True, "max_consecutive": 0,
                   "action": "raise", "manager": None}
        while self._guard_pending:
            scalar, step = self._guard_pending.popleft()
            self._note_guard(int(scalar), cfg, step=step)

    def _guard_cfg(self):
        """Active guard config dict, or None when the guard is off
        (explicit set_nonfinite_guard wins over the env knobs)."""
        if self._guard is not None:
            return self._guard if self._guard["enabled"] else None
        from ..config import get_env
        if get_env("MXNET_GUARD_NONFINITE"):
            return {"enabled": True,
                    "max_consecutive": get_env("MXNET_GUARD_MAX_BAD_STEPS"),
                    "action": "raise", "manager": None}
        return None

    def _grads_nonfinite(self):
        """Host-side guard check for the legacy path: any NaN/Inf in
        any device's reduced-to-be gradients or outputs."""
        import jax.numpy as jnp

        def _bad(arr):
            data = getattr(arr, "_data", None)
            return (data is not None
                    and jnp.issubdtype(data.dtype, jnp.inexact)
                    and bool(jnp.logical_not(
                        jnp.all(jnp.isfinite(data)))))

        group = self._exec_group
        for ex in group.execs:
            for name in group.param_names:
                if group.grad_req[name] == "null":
                    continue
                if _bad(ex.grad_dict.get(name)):
                    return True
            for out in ex.outputs:
                if _bad(out):
                    return True
        return False

    def _note_guard(self, skipped, guard, step=None):
        """Account one guarded step; fire the divergence action after
        max_consecutive bad steps in a row.  *step* is the step_seq
        the flag belongs to — deferred readbacks
        (MXNET_GUARD_READBACK_LAG) resolve after later steps have
        dispatched, so the event must carry the dispatch-time stamp."""
        if step is None:
            step = self._step_seq
        if not skipped:
            self._guard_consec = 0
            return
        from .. import profiler as _prof
        from ..observability import events as _obs_events
        self._guard_skipped += 1
        self._guard_consec += 1
        _prof.bump_counter("guard_skipped_steps")
        _obs_events.emit("guard", step=step,
                         consecutive=self._guard_consec,
                         total_skipped=self._guard_skipped)
        self.logger.warning(
            "non-finite loss/gradients: optimizer update skipped "
            "(%d consecutive, %d total)", self._guard_consec,
            self._guard_skipped)
        limit = guard.get("max_consecutive") or 0
        if limit and self._guard_consec >= limit:
            self._guard_consec = 0
            self._on_divergence(guard, step=step)

    def _on_divergence(self, guard, step=None):
        from ..resilience import DivergenceError
        from ..observability import events as _obs_events
        action = guard.get("action", "raise")
        _obs_events.emit(
            "guard", divergence=True,
            step=self._step_seq if step is None else step,
            action=action if isinstance(action, str) else "callable",
            total_skipped=self._guard_skipped)
        if callable(action):
            action(self)
            return
        if action == "rollback":
            mgr = guard.get("manager")
            rec = mgr.restore_latest() if mgr is not None else None
            if rec is None:
                raise DivergenceError(
                    "training diverged (%d consecutive non-finite "
                    "steps) and no intact checkpoint is available to "
                    "roll back to" % (guard.get("max_consecutive") or 0))
            _, arg_params, aux_params = rec.load()
            self.set_params(arg_params, aux_params)
            if rec.states_path is not None and self.optimizer_initialized:
                self.load_optimizer_states(rec.states_path)
            self.logger.warning(
                "training diverged: rolled back to checkpoint epoch %d "
                "(%s)", rec.epoch, rec.params_path)
            return
        raise DivergenceError(
            "training diverged: %d consecutive steps had non-finite "
            "loss/gradients (%d skipped in total); lower the learning "
            "rate, enable rollback, or inspect the data pipeline"
            % (guard.get("max_consecutive") or 0, self._guard_skipped))

    # -- fused train step --------------------------------------------------
    def forward_backward_update(self, data_batch):
        """One training step.  When eligible (no kvstore or a local
        one, a local Updater, and an optimizer with a tree-level kernel
        mapping — optimizer/tree_opt.py), this runs the FUSED path:

        * single device: the whole step — forward, VJP, optimizer
          update — is ONE donated XLA program
          (``Executor.init_fused_step``), so the ~O(params) per-step
          eager dispatches of the legacy loop collapse to one, and
          weights/momenta stay device-resident across steps;
        * multiple devices: per-device forward_backward programs, then
          the per-name ``Updater`` loop collapses to one jitted tree
          update between ``reduce_grads()`` (or kvstore push/pull) and
          ``broadcast_params()``.

        Falls back to ``forward_backward()`` + ``update()`` for dist
        kvstores, ``update_on_kvstore``, installed monitors,
        ``inputs_need_grad``, non-'write' grad_req, and optimizers
        without a tree mapping.  Disable with
        ``MXNET_MODULE_FUSED_STEP=0``.

        .. note:: on the full-fused path the gradients live only
           inside the XLA program — ``grad_dict`` / bind-time
           ``args_grad`` aliases are NOT refreshed (same opacity as a
           captured CUDA graph).  Callbacks that inspect per-step
           gradients must disable fusion or call
           ``forward_backward()`` + ``update()`` themselves.
        """
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._forward_pad = 0
        from ..resilience import chaos
        # crash-anywhere drill hooks: kill_at_step / hang_at_step fire
        # at the START of the (resumable) global step
        chaos.on_train_step(self._step_seq)
        data_batch = chaos.maybe_poison_batch(data_batch, self._step_seq)
        self._step_seq += 1
        guard = self._guard_cfg()
        if not self._fused_ok():
            self._legacy_step(data_batch, guard)
            return
        if self._fused is None:
            self._setup_fused()
        if self._fused is False:
            self._legacy_step(data_batch, guard)
            return
        from ..optimizer import tree_opt
        if self._fused["hyper"] != tree_opt.hyper_sig(self._optimizer):
            # a baked-in hyper-param (rescale_grad, momentum, ...) was
            # mutated mid-run — the legacy loop re-reads these every
            # step, so rebuild the program instead of applying the
            # stale constant (the state tree stays valid)
            self._fused = None
            self._setup_fused()
        if self._fused["guard"] != (guard is not None):
            # guard toggled mid-run (set_nonfinite_guard or the env
            # knob): the guard is compiled into the program; deferred
            # readbacks from the old program settle first
            self.drain_guard_readbacks()
            self._fused = None
            self._setup_fused()
        if self._fused_state is None:
            self._import_fused_state()
        if self._fused["mode"] == "full":
            self._run_fused_full(data_batch)
        else:
            self._run_fused_partial(data_batch)

    def _legacy_step(self, data_batch, guard):
        """forward_backward + update, with the host-side mirror of the
        in-graph guard when one is configured (the composed path keeps
        subclass overrides live, so the check must stay outside)."""
        # a path switch (fused -> legacy mid-run) settles any deferred
        # fused-path readbacks before this step's synchronous check
        self.drain_guard_readbacks()
        aux_snap = self._snapshot_aux() if guard is not None else None
        self.forward_backward(data_batch)
        if guard is not None and self._grads_nonfinite():
            # forward already rebound aux (BatchNorm running stats) to
            # NaN-poisoned arrays — restore the pre-step handles so the
            # skip really is a no-op, matching the fused path
            self._restore_aux(aux_snap)
            self._note_guard(1, guard)   # update skipped entirely
            return
        self.update()
        if guard is not None:
            self._note_guard(0, guard)

    def _snapshot_aux(self):
        """Pre-step aux array handles, per exec.  jax arrays are
        immutable and aux updates REBIND ``_data``, so this is
        reference capture — no copy."""
        return [{n: a._data for n, a in ex.aux_dict.items()}
                for ex in self._exec_group.execs]

    def _restore_aux(self, snapshot):
        for ex, snap in zip(self._exec_group.execs, snapshot):
            for n, data in snap.items():
                ex.aux_dict[n]._data = data

    def _fused_ok(self):
        from ..config import get_env
        if not get_env("MXNET_MODULE_FUSED_STEP"):
            return False
        cls = type(self)
        if cls.forward_backward is not Module.forward_backward \
                or cls.update is not Module.update \
                or cls.forward is not Module.forward \
                or cls.backward is not Module.backward:
            # a subclass customizing any stage (e.g. SVRGModule's
            # variance-reduced gradient rewrite, or a backward override
            # that clips grads) composes them — the fused program runs
            # the whole step in one XLA call and would silently skip
            # the override
            return False
        if self._updater is None:
            return False       # update_on_kvstore: state lives store-side
        if self._kvstore is not None and \
                "dist" in getattr(self._kvstore, "type", ""):
            return False
        if self._monitor is not None or self.inputs_need_grad:
            return False
        if self._grad_req != "write":
            return False       # 'add' accumulation breaks donation
        from ..optimizer import tree_opt
        return tree_opt.supports_fused(self._optimizer)

    def _setup_fused(self):
        from ..optimizer import tree_opt
        group = self._exec_group
        ex0 = group.execs[0]
        names = [n for n in group.param_names
                 if group.grad_req[n] != "null"]
        if not names:
            self._fused = False
            return
        if any(ex._group2ctx for ex in group.execs):
            # group2ctx places parameters on different devices; one
            # jitted tree update cannot span them — the legacy loop's
            # per-param dispatch lands on each param's device
            self._fused = False
            return
        # updater indices are positions in param_names (see update())
        idx_of = {n: i for i, n in enumerate(group.param_names)}
        # COMMIT params/aux to the executor device before the first
        # fused call: initializer-produced arrays are uncommitted, the
        # program's outputs are committed, and jax keys its jit cache
        # on committedness — left alone, step 2 silently recompiled the
        # entire fused program a second time (found by the graftsan
        # recompile sanitizer; device_put on an on-device array is
        # zero-copy)
        import jax as _jax
        dev = ex0._ctx.jax_device
        for n in names:
            arr = ex0.arg_dict[n]._data
            if not getattr(arr, "_committed", True):
                ex0.arg_dict[n]._data = _jax.device_put(arr, dev)
        for a in ex0.aux_dict.values():
            if not getattr(a._data, "_committed", True):
                a._data = _jax.device_put(a._data, dev)
        tree_update = tree_opt.make_tree_update(self._optimizer)
        guard = self._guard_cfg() is not None
        ctx = {"names": names, "idx": idx_of, "guard": guard,
               "hyper": tree_opt.hyper_sig(self._optimizer)}
        from .. import sanitizer as _sanitizer
        if len(group.execs) == 1 and self._kvstore is None and \
                ex0._train_step_fn is not None:
            from ..ops.registry import supports_donation
            ctx["mode"] = "full"
            ctx["donates"] = supports_donation()
            from ..observability import events as _obs_events
            raw = ex0.init_fused_step(tree_update, guard_nonfinite=guard)
            # the un-wrapped jit: the MXNET_IR_AUDIT hook lowers
            # through it (the watch/wrap layers have no .lower)
            ctx["raw_fn"] = raw
            ctx["fn"] = _obs_events.watch_jit(_sanitizer.wrap_jit(
                raw, "fused_step"), "fused_step")
        else:
            import jax
            from .. import profiler as _prof
            inner = tree_opt.guarded_tree_update(tree_update) if guard \
                else tree_update

            def tree_apply(grads, params, state, lrs, wds, ts):
                # trace-time only: the compile counter for this program
                _prof.bump_counter(  # graftlint: disable=JG003
                    "tree_apply_compiles")  # trace-time-only on purpose
                return inner(grads, params, state, lrs, wds, ts)

            from ..ops.registry import supports_donation
            # donate params + optimizer state (argnums 1 and 2)
            donate = (1, 2) if supports_donation() else ()
            ctx["mode"] = "partial"
            ctx["donates"] = bool(donate)
            from ..observability import events as _obs_events
            raw = jax.jit(tree_apply, donate_argnums=donate)
            ctx["raw_fn"] = raw
            ctx["fn"] = _obs_events.watch_jit(_sanitizer.wrap_jit(
                raw, "tree_apply"), "tree_apply")
        self._fused = ctx

    def _import_fused_state(self):
        """Legacy Updater state -> device-resident tree (fresh zeros for
        indices the updater has not seen — its own lazy-create rule)."""
        from ..optimizer import tree_opt
        from ..ops.registry import supports_donation
        import jax as _jax
        ex0 = self._exec_group.execs[0]
        dev = ex0._ctx.jax_device
        # device_put COMMITS the leaf (not just places it): an
        # uncommitted state leaf at step 1 vs the committed program
        # output at step 2 would flip the jit cache key and recompile
        # the whole fused program (see _setup_fused)
        put = lambda a: _jax.device_put(a, dev)
        if supports_donation():
            # the first fused step DONATES these buffers, and the
            # Updater's NDArrays alias them (import rebinds handles) —
            # copy so updater.states never points at deleted arrays
            import jax.numpy as jnp
            put = lambda a: _jax.device_put(jnp.array(a), dev)
        params_nd = {n: ex0.arg_dict[n] for n in self._fused["names"]}
        self._fused_state = tree_opt.import_from_updater(
            self._updater, self._optimizer, params_nd,
            self._fused["idx"], put=put)

    def _sync_fused_to_updater(self):
        """Export the device state tree into Updater.states (handle
        rebinding only) so get_states / save_optimizer_states serialize
        the exact legacy per-index format."""
        if self._fused_state is not None and self._fused and \
                self._updater is not None:
            from ..optimizer import tree_opt
            from ..ops.registry import supports_donation
            tree_opt.export_to_updater(self._fused_state, self._updater,
                                       self._fused["idx"],
                                       copy=supports_donation())

    def _run_fused_full(self, data_batch):
        from ..optimizer import tree_opt
        from .. import profiler as _prof
        from ..executor import _wrap_out
        from ..ndarray.ndarray import _as_nd
        ctx = self._fused
        group = self._exec_group
        ex = group.execs[0]
        names = ctx["names"]
        data = _as_list(data_batch.data)
        labels = _as_list(data_batch.label) if data_batch.label else []
        for name, arr in zip(group.data_names, data):
            dst = ex.arg_dict[name]
            dst._data = ex._place(
                _as_nd(arr)._data.astype(dst.dtype))
        for name, arr in zip(group.label_names, labels):
            if name in ex.arg_dict:
                dst = ex.arg_dict[name]
                dst._data = ex._place(
                    _as_nd(arr)._data.astype(dst.dtype))
        # a prior forward(is_train=True) snapshotted raw param buffers
        # for backward() replay — this step donates exactly those, so
        # the snapshot must not outlive it
        ex._pending = None
        arg_map = ex._arg_map()
        params = {n: arg_map[n] for n in names}
        rest = {n: v for n, v in arg_map.items() if n not in params}
        ts, lrs, wds = tree_opt.host_hyper(self._optimizer, names,
                                           ctx["idx"])
        from .. import sanitizer as _sanitizer
        donated = None
        if ctx.get("donates") and _sanitizer.enabled("donation"):
            import jax as _jax
            donated = list(params.values()) + \
                _jax.tree_util.tree_leaves(self._fused_state)
        # the PRNG key folds in THIS module's update count, which
        # advances every step — num_update only ratchets via max() and
        # can stall when the optimizer is shared with a module trained
        # further, which would replay the same dropout masks
        from .. import iraudit as _iraudit
        if _iraudit.enabled() and not ctx.get("ir_audited"):
            # first dispatch only: one extra trace (lower() does not
            # execute or consume the args), zero cost when the knob
            # is off
            ctx["ir_audited"] = True
            import jax as _jax
            n_don = (len(_jax.tree_util.tree_leaves(params)) +
                     len(_jax.tree_util.tree_leaves(self._fused_state))
                     ) if ctx.get("donates") else None
            _iraudit.audit(
                "train", "fused_step",
                ctx["raw_fn"].lower(
                    params, rest, ex._aux_map(), ex._key,
                    self._fused_state, lrs, wds, ts,
                    max(ts.values())).as_text(),
                hot_path=True, donated=n_don, budget=1)
        import time as _time
        t0 = _time.perf_counter()
        with _sanitizer.transfer_guard("fused train step"):
            res = ctx["fn"](
                params, rest, ex._aux_map(), ex._key, self._fused_state,
                lrs, wds, ts, max(ts.values()))
        # async dispatch latency: host time to ISSUE the one donated
        # program (execution completes on-device; a blow-up here means
        # tracing/recompiling snuck into the step)
        _FUSED_STEP_SECONDS.observe(_time.perf_counter() - t0)
        if ctx["guard"]:
            outs, new_aux, new_params, new_state, skipped = res
        else:
            outs, new_aux, new_params, new_state = res
        _prof.bump_counter("fused_step_dispatches")
        self._fused_state = new_state
        # rebind the bind-time containers in place: every alias (shared
        # modules, C-ABI handles) sees the new buffers, and the donated
        # old ones are never touched again
        for n in names:
            ex.arg_dict[n]._data = new_params[n]
        for n, v in new_aux.items():
            ex.aux_dict[n]._data = v
        ex.outputs = [_wrap_out(o) for o in outs]
        if donated is not None:
            # every framework container is rebound above — any NDArray
            # still holding one of the donated buffers is a stale alias
            _sanitizer.poison_donated(
                donated, "the fused train step (step %d)"
                % self._step_seq)
        self._params_dirty = True
        if ctx["guard"]:
            # sync (lag 0) or bounded-lag async accounting of the
            # in-graph skip flag — the param-protecting where-select
            # already ran on-device either way (docs/resilience.md,
            # docs/perf_input_pipeline.md)
            self._account_guard(skipped, self._guard_cfg())

    def _run_fused_partial(self, data_batch):
        from ..optimizer import tree_opt
        from .. import profiler as _prof
        from ..ndarray.sparse import BaseSparseNDArray
        ctx = self._fused
        group = self._exec_group
        ex0 = group.execs[0]
        names = ctx["names"]
        aux_snap = self._snapshot_aux() if ctx["guard"] else None
        group.forward_backward(data_batch)
        # the jitted tree update donates ex0's param buffers — a stale
        # forward(is_train=True) snapshot must not outlive them (same
        # rule as the full-fused path)
        ex0._pending = None
        self._aggregate_grads(group)
        grads = {}
        for n in names:
            g = ex0.grad_dict[n]
            if isinstance(g, BaseSparseNDArray):
                grads[n] = (g._aux[0], g._data)   # rsp (ids, vals)
            else:
                grads[n] = g._data
        params = {n: ex0.arg_dict[n]._data for n in names}
        ts, lrs, wds = tree_opt.host_hyper(self._optimizer, names,
                                           ctx["idx"])
        from .. import sanitizer as _sanitizer
        donated = None
        if ctx.get("donates") and _sanitizer.enabled("donation"):
            import jax as _jax
            donated = list(params.values()) + \
                _jax.tree_util.tree_leaves(self._fused_state)
        import time as _time
        from .. import iraudit as _iraudit
        if _iraudit.enabled() and not ctx.get("ir_audited"):
            ctx["ir_audited"] = True
            import jax as _jax
            n_don = (len(_jax.tree_util.tree_leaves(params)) +
                     len(_jax.tree_util.tree_leaves(self._fused_state))
                     ) if ctx.get("donates") else None
            _iraudit.audit(
                "train", "tree_apply",
                ctx["raw_fn"].lower(grads, params, self._fused_state,
                                    lrs, wds, ts).as_text(),
                hot_path=True, donated=n_don, budget=1)
        t0 = _time.perf_counter()
        with _sanitizer.transfer_guard("partial-fused tree update"):
            res = ctx["fn"](grads, params, self._fused_state, lrs, wds,
                            ts)
        _TREE_APPLY_SECONDS.observe(_time.perf_counter() - t0)
        if ctx["guard"]:
            new_params, new_state, skipped = res
        else:
            new_params, new_state = res
        _prof.bump_counter("tree_apply_dispatches")
        self._fused_state = new_state
        for n in names:
            ex0.arg_dict[n]._data = new_params[n]
        group.broadcast_params()
        if donated is not None:
            _sanitizer.poison_donated(
                donated, "the partial-fused tree update (step %d)"
                % self._step_seq)
        self._params_dirty = True
        if ctx["guard"]:
            skipped = int(skipped)
            if skipped:
                # the per-device forward_backward already rebound aux
                # (BatchNorm stats) to this bad step's values — restore
                self._restore_aux(aux_snap)
            self._note_guard(skipped, self._guard_cfg())

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        outs = self._exec_group.get_outputs(merge_multi_context)
        pad = self._forward_pad
        if pad and merge_multi_context:
            # remainder fix-up (see _pad_remainder_batch): mask off the
            # zero-padded rows so callers see the natural batch
            bs = self._exec_group.batch_size
            outs = [o[:bs - pad]
                    if getattr(o, "shape", None) and o.shape and
                    o.shape[0] == bs else o
                    for o in outs]
        return outs

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        grads = []
        for name in self._data_names:
            per_dev = [ex.grad_dict[name] for ex in
                       self._exec_group.execs]
            if len(per_dev) == 1 or not merge_multi_context:
                grads.append(per_dev[0] if merge_multi_context else per_dev)
            else:
                grads.append(nd.concatenate(
                    [g.as_in_context(self._context[0]) for g in per_dev],
                    axis=0))
        return grads

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        outputs = self.get_outputs()
        eval_metric.update(labels, outputs[:len(labels)]
                           if labels else outputs)

    def install_monitor(self, mon):
        assert self.binded
        self._monitor = mon    # per-op taps need the legacy step path
        for ex in self._exec_group.execs:
            mon.install(ex)

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded
        arg_params, aux_params = self.get_params()
        self.bind(data_shapes, label_shapes, self.for_training,
                  self.inputs_need_grad, force_rebind=True)
        self._set_exec_params(arg_params, aux_params)
