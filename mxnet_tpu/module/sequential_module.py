"""SequentialModule — chain modules imperatively (reference:
python/mxnet/module/sequential_module.py:28).

Each child binds against the previous child's output shapes; data flows
through the chain on forward, gradients flow back in reverse on
backward.  Children flagged ``take_labels=True`` receive the original
batch labels."""

from __future__ import annotations

import logging

from ..io.io import DataBatch, DataDesc
from .base_module import BaseModule

__all__ = ["SequentialModule"]


class SequentialModule(BaseModule):
    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._data_shapes = None
        self._label_shapes = None
        self._meta_keys = {self.META_TAKE_LABELS, self.META_AUTO_WIRING}

    def add(self, module, **kwargs):
        for key in kwargs:
            assert key in self._meta_keys, \
                "unknown meta %r (known: %s)" % (key, self._meta_keys)
        self._modules.append(module)
        self._metas.append(kwargs)
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    @property
    def data_names(self):
        return self._modules[0].data_names if self._modules else []

    @property
    def output_names(self):
        return self._modules[-1].output_names if self._modules else []

    @property
    def data_shapes(self):
        assert self.binded
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._modules[-1].output_shapes

    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params, aux_params = {}, {}
        for module in self._modules:
            arg, aux = module.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return arg_params, aux_params

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        for module in self._modules:
            module.init_params(initializer=initializer,
                               arg_params=arg_params,
                               aux_params=aux_params,
                               allow_missing=True,
                               force_init=force_init,
                               allow_extra=True)
        self.params_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            return
        assert len(self._modules) > 0
        assert shared_module is None, \
            "shared_module is not supported for SequentialModule"
        self._label_shapes = label_shapes
        my_data_shapes = data_shapes
        anybody_ever_needs_label = False
        for i, (module, meta) in enumerate(zip(self._modules,
                                               self._metas)):
            meta_labels = meta.get(self.META_TAKE_LABELS, False)
            if meta_labels:
                anybody_ever_needs_label = True
            module.bind(
                data_shapes=my_data_shapes,
                label_shapes=label_shapes if meta_labels else None,
                for_training=for_training,
                # interior modules need input grads to continue the chain
                inputs_need_grad=(inputs_need_grad if i == 0
                                  else for_training),
                force_rebind=force_rebind, grad_req=grad_req)
            # next module consumes this module's outputs; shapes come
            # from symbol inference (executor outputs don't exist yet)
            sym = getattr(module, "symbol", None)
            if sym is not None:
                in_shapes = {d.name: d.shape for d in
                             (DataDesc(*s) if not isinstance(s, DataDesc)
                              else s for s in my_data_shapes)}
                _, out_shapes, _ = sym.infer_shape(**in_shapes)
                my_data_shapes = [
                    DataDesc(name, shape) for name, shape in
                    zip(sym.list_outputs(), out_shapes)]
            else:
                my_data_shapes = [
                    DataDesc(name, shape) for name, shape in
                    zip(module.output_names,
                        [d.shape if hasattr(d, "shape") else d[1]
                         for d in module.output_shapes])]
        if not anybody_ever_needs_label:
            self._label_shapes = None
        self.binded = True
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        for module in self._modules:
            module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                  optimizer_params=optimizer_params,
                                  force_init=force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        batch = data_batch
        for i, module in enumerate(self._modules):
            module.forward(batch, is_train=is_train)
            if i == len(self._modules) - 1:
                break
            batch = DataBatch(data=module.get_outputs(),
                              label=data_batch.label,
                              pad=getattr(data_batch, "pad", 0))

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        for i, module in reversed(list(enumerate(self._modules))):
            module.backward(out_grads=out_grads)
            if i == 0:
                break
            out_grads = module.get_input_grads()

    def update(self):
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        for module in self._modules:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.inputs_need_grad
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        assert self.binded and self.params_initialized
        for meta, module in zip(self._metas, self._modules):
            if meta.get(self.META_TAKE_LABELS, False):
                module.update_metric(eval_metric, labels)
        # the tail module scores even without labels meta, matching
        # common usage where only the head takes labels
        if not any(m.get(self.META_TAKE_LABELS, False)
                   for m in self._metas):
            self._modules[-1].update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        assert self.binded
        for module in self._modules:
            module.install_monitor(mon)
