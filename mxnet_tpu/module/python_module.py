"""PythonModule / PythonLossModule — write modules in pure Python
(reference: python/mxnet/module/python_module.py:28, :243).

PythonModule handles the bind/param bookkeeping for parameter-free
modules computed on the Python side; PythonLossModule turns a Python
loss/gradient function pair into the tail of a module chain
(typically inside a SequentialModule)."""

from __future__ import annotations

import logging

import numpy as _np

from .. import ndarray as nd
from ..io.io import DataDesc
from .base_module import BaseModule

__all__ = ["PythonModule", "PythonLossModule"]


class PythonModule(BaseModule):
    """Subclass and implement forward/backward (+ _compute_output_shapes
    when outputs differ from inputs)."""

    def __init__(self, data_names, label_names, output_names,
                 logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    def get_params(self):
        return {}, {}

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        self.params_initialized = True

    def update(self):
        pass

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        assert grad_req == "write"
        self._data_shapes = [
            d if isinstance(d, DataDesc) else DataDesc(*d)
            for d in data_shapes]
        self._label_shapes = ([
            d if isinstance(d, DataDesc) else DataDesc(*d)
            for d in label_shapes] if label_shapes else None)
        self._output_shapes = self._compute_output_shapes()
        self.binded = True

    def _compute_output_shapes(self):
        """Default: outputs mirror the inputs 1:1."""
        assert len(self._data_shapes) == len(self._output_names)
        return [DataDesc(name, d.shape) for name, d in
                zip(self._output_names, self._data_shapes)]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        if self._label_shapes is None:
            return
        eval_metric.update(labels, self.get_outputs())


class PythonLossModule(PythonModule):
    """Python-side loss: forward stores the scores, backward calls
    *grad_func* (or the default softmax-CE gradient)
    (reference: python_module.py:243)."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names, label_names,
                         [name + "_output"], logger=logger)
        self._name = name
        assert len(data_names) == 1
        self._scores = None
        self._labels = None
        self._scores_grad = None
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        return [DataDesc(self._name + "_output",
                         self._data_shapes[0].shape)]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if is_train is None:
            is_train = self.for_training
        if is_train and data_batch.label:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        assert merge_multi_context
        return [self._scores]

    def backward(self, out_grads=None):
        assert out_grads is None, \
            "PythonLossModule is a loss head; out_grads not accepted"
        assert self.for_training
        if self._grad_func is not None:
            grad = self._grad_func(self._scores, self._labels)
            if not isinstance(grad, nd.NDArray):
                grad = nd.array(grad)
            self._scores_grad = grad
        else:
            # default: softmax cross-entropy gradient (prob - onehot)
            prob = nd.softmax(self._scores)
            onehot = nd.one_hot(self._labels, prob.shape[1])
            self._scores_grad = prob - onehot

    def get_input_grads(self, merge_multi_context=True):
        assert merge_multi_context
        return [self._scores_grad]

    def install_monitor(self, mon):
        raise NotImplementedError()
