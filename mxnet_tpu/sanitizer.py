"""Bridge to the graftsan runtime sanitizer suite (tools/graftsan).

Production code never imports ``tools.graftsan`` directly — it calls
the factories and hooks here, which fall through to the plain
``threading``/``queue`` primitives (or to no-ops) unless the matching
component is enabled via ``MXNET_SAN`` (comma list of
``race,recompile,donation,transfer,sched``, or ``all``).  The off-path cost
is one environment read at *creation* time and zero per access, so
the wrappers can stay threaded through the hot subsystems
unconditionally.

``MXNET_SAN`` is consulted at call time (not import time) so the
pytest ``--graftsan`` flag and per-test monkeypatching work; objects
created while a component is off stay uninstrumented.

The graftsan implementation lives in the repo's ``tools/`` tree (it is
developer tooling, like graftlint); when the package is used without
that tree, enabling ``MXNET_SAN`` raises a clear error instead of
silently sanitizing nothing.
"""

from __future__ import annotations

import contextlib
import os
import queue as _queue
import threading as _threading

__all__ = ["enabled", "lock", "rlock", "condition", "event", "queue",
           "thread", "track", "sched_point", "wrap_jit",
           "poison_donated", "transfer_guard", "transfer_check"]

_VALID = ("race", "recompile", "donation", "transfer", "sched")


def enabled(component):
    """Is a sanitizer component on?  (read from env each call)"""
    raw = os.environ.get("MXNET_SAN", "").strip().lower()
    if not raw or raw in ("0", "off", "none", "false"):
        return False
    if raw in ("1", "on", "all", "true"):
        return True
    return component in {p.strip() for p in raw.split(",")}


def _graftsan():
    """Import tools.graftsan (repo-root layout) with a clear failure."""
    try:
        import tools.graftsan as g
        return g
    except ImportError:
        import sys
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if root not in sys.path and \
                os.path.isdir(os.path.join(root, "tools", "graftsan")):
            sys.path.insert(0, root)
            import tools.graftsan as g
            return g
        raise RuntimeError(
            "MXNET_SAN is set but the graftsan suite (tools/graftsan) "
            "is not importable — run from a repo checkout, or unset "
            "MXNET_SAN")


def _sched():
    """The graftsched scheduler controlling the calling thread, or
    None.  Three gates, cheapest first: the ``sched`` component must
    be on, ``tools.graftsched.core`` must be importable, and a
    scheduler must be installed with the *calling thread* under its
    control.  ``MXNET_SAN=all`` therefore never reroutes ordinary
    code — only threads a graftsched explorer itself spawned."""
    if not enabled("sched"):
        return None
    try:
        import tools.graftsched.core as core
    except ImportError:
        import sys
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if root not in sys.path and \
                os.path.isdir(os.path.join(root, "tools", "graftsched")):
            sys.path.insert(0, root)
            import tools.graftsched.core as core
        else:
            return None
    return core.current_controlled()


# -- race / sched: instrumented primitive factories --------------------------

def lock(label=None):
    s = _sched()
    if s is not None:
        return s.make_lock(label)
    if enabled("race"):
        return _graftsan().race.lock(label)
    return _threading.Lock()


def rlock(label=None):
    s = _sched()
    if s is not None:
        return s.make_rlock(label)
    if enabled("race"):
        return _graftsan().race.rlock(label)
    return _threading.RLock()


def condition(lock=None, label=None):
    s = _sched()
    if s is not None:
        return s.make_condition(lock, label)
    if enabled("race"):
        return _graftsan().race.condition(lock, label)
    return _threading.Condition(lock)


def event():
    s = _sched()
    if s is not None:
        return s.make_event()
    return _threading.Event()


def queue(maxsize=0):
    s = _sched()
    if s is not None:
        return s.make_queue(maxsize)
    if enabled("race"):
        return _graftsan().race.queue_(maxsize)
    return _queue.Queue(maxsize)


def thread(group=None, target=None, name=None, args=(), kwargs=None,
           daemon=None):
    s = _sched()
    if s is not None:
        return s.make_thread(target=target, name=name, args=args,
                             kwargs=kwargs, daemon=daemon)
    if enabled("race"):
        return _graftsan().race.thread(group=group, target=target,
                                       name=name, args=args,
                                       kwargs=kwargs, daemon=daemon)
    # a factory hands ownership to its caller — the join/daemon
    # obligation (JG011) sits at the call site, not here
    return _threading.Thread(group=group, target=target,  # graftlint: disable=JG011
                             name=name, args=args, kwargs=kwargs or {},
                             daemon=daemon)


def track(obj, attrs, label=None):
    """Register *attrs* of *obj* with the lockset race tracker (or,
    under a graftsched run, with the schedule explorer's per-object
    access recorder).  Call at the end of ``__init__``; no-op when
    both components are off."""
    s = _sched()
    if s is not None:
        return s.track_object(obj, attrs, label)
    if enabled("race"):
        _graftsan().race.track_object(obj, attrs, label)
    return obj


def sched_point(label=None):
    """Explicit schedule yield point for graftsched scenarios; no-op
    (one env read) unless the calling thread is under an installed
    graftsched scheduler."""
    s = _sched()
    if s is not None:
        s.explicit_point(label)


# -- recompile ---------------------------------------------------------------

def wrap_jit(fn, name):
    """Watch a jitted callable for blamed cache misses; identity when
    the recompile component is off."""
    if enabled("recompile"):
        return _graftsan().recompile.wrap_jit(fn, name)
    return fn


# -- donation ----------------------------------------------------------------

def poison_donated(donated_leaves, site):
    """After a donating dispatch: poison every stale NDArray alias of
    *donated_leaves* so use-after-donate raises at the touch site."""
    if enabled("donation"):
        from .ndarray import NDArray
        return _graftsan().donation.poison_stale_aliases(
            donated_leaves, site, ndarray_cls=NDArray)
    return 0


# -- transfer ----------------------------------------------------------------

def transfer_guard(label="hot path"):
    """Context manager: device→host syncs inside raise.  nullcontext
    when the transfer component is off."""
    if enabled("transfer"):
        return _graftsan().transfer.guard(label)
    return contextlib.nullcontext()


def transfer_check(what, shape=None):
    """d2h choke-point hook (NDArray.asnumpy).  The caller guards on
    :data:`_transfer_possible` so the off-path cost is one module
    attribute read."""
    _graftsan().transfer.check(what, shape)


def _transfer_active():
    """Is the calling thread inside a transfer-guarded region?"""
    if not enabled("transfer"):
        return False
    return _graftsan().transfer.active()
