"""Evaluation metrics (reference: python/mxnet/metric.py, 1,424 LoC).

Registry of EvalMetrics updated per batch; host-side numpy math (metrics are
not on the training hot path — outputs are already device arrays, one
``asnumpy`` sync per batch like the reference's update_metric)."""

from __future__ import annotations

import math

import numpy as _np

from .base import registry as _registry
from .ndarray import NDArray

_reg = _registry("metric")

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Perplexity",
           "Loss", "Torch", "Caffe", "CustomMetric", "np", "create",
           "register"]


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


def check_label_shapes(labels, preds, shape=False):
    if not shape:
        label_shape, pred_shape = len(labels), len(preds)
    else:
        label_shape, pred_shape = labels.shape, preds.shape
    if label_shape != pred_shape:
        raise ValueError("Shape of labels {} does not match shape of "
                         "predictions {}".format(label_shape, pred_shape))


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    def get_config(self):
        config = dict(self._kwargs)
        config.update({"metric": type(self).__name__, "name": self.name,
                       "output_names": self.output_names,
                       "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names if name in pred]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names
                     if name in label]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


def register(klass=None, name=None, aliases=()):
    if klass is None:
        return lambda k: register(k, name, aliases)
    _reg.register(klass, name=name, aliases=aliases)
    return klass


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    return _reg.get(metric)(*args, **kwargs)


@register
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            if isinstance(name, str):
                name = [name]
            if isinstance(value, (float, int)):
                value = [value]
            names.extend(name)
            values.extend(value)
        return (names, values)


@register(aliases=("acc",))
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        if isinstance(labels, NDArray):
            labels = [labels]
        if isinstance(preds, NDArray):
            preds = [preds]
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            p = _as_np(pred)
            l = _as_np(label).astype("int32")
            if p.ndim > l.ndim:
                p = p.argmax(axis=self.axis)
            p = p.astype("int32").reshape(-1)
            l = l.reshape(-1)
            self.sum_metric += (p == l).sum()
            self.num_inst += len(l)


@register(aliases=("top_k_accuracy", "top_k_acc"))
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        self.top_k = top_k
        self.name += "_%d" % top_k

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            p = _as_np(pred)
            l = _as_np(label).astype("int32")
            num_samples = p.shape[0]
            num_dims = p.ndim
            if num_dims == 1:
                self.sum_metric += (p.astype("int32") == l).sum()
            else:
                topk = _np.argpartition(p, -self.top_k,
                                        axis=-1)[:, -self.top_k:]
                for j in range(self.top_k):
                    self.sum_metric += (topk[:, j] == l).sum()
            self.num_inst += num_samples


@register
class F1(EvalMetric):
    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names)
        self.average = average
        self._tp = self._fp = self._fn = 0.0

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = 0.0

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            p = _as_np(pred)
            l = _as_np(label).astype("int32").reshape(-1)
            if p.ndim > 1:
                p = p.argmax(axis=1)
            p = p.astype("int32").reshape(-1)
            self._tp += ((p == 1) & (l == 1)).sum()
            self._fp += ((p == 1) & (l == 0)).sum()
            self._fn += ((p == 0) & (l == 1)).sum()
            precision = self._tp / max(self._tp + self._fp, 1e-12)
            recall = self._tp / max(self._tp + self._fn, 1e-12)
            f1 = 2 * precision * recall / max(precision + recall, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1


@register
class MCC(EvalMetric):
    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names)
        self._tp = self._fp = self._tn = self._fn = 0.0

    def reset(self):
        super().reset()
        self._tp = self._fp = self._tn = self._fn = 0.0

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            p = _as_np(pred)
            l = _as_np(label).astype("int32").reshape(-1)
            if p.ndim > 1:
                p = p.argmax(axis=1)
            p = p.astype("int32").reshape(-1)
            self._tp += ((p == 1) & (l == 1)).sum()
            self._fp += ((p == 1) & (l == 0)).sum()
            self._tn += ((p == 0) & (l == 0)).sum()
            self._fn += ((p == 0) & (l == 1)).sum()
            terms = ((self._tp + self._fp) * (self._tp + self._fn) *
                     (self._tn + self._fp) * (self._tn + self._fn))
            denom = math.sqrt(terms) if terms > 0 else 1.0
            self.sum_metric = (self._tp * self._tn -
                               self._fp * self._fn) / denom
            self.num_inst = 1


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            l = _as_np(label)
            p = _as_np(pred)
            if l.ndim == p.ndim - 1:
                l = l.reshape(l.shape + (1,))
            self.sum_metric += _np.abs(l - p).mean()
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            l = _as_np(label)
            p = _as_np(pred)
            if l.ndim == p.ndim - 1:
                l = l.reshape(l.shape + (1,))
            self.sum_metric += ((l - p) ** 2).mean()
            self.num_inst += 1


@register
class RMSE(EvalMetric):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            l = _as_np(label)
            p = _as_np(pred)
            if l.ndim == p.ndim - 1:
                l = l.reshape(l.shape + (1,))
            self.sum_metric += math.sqrt(((l - p) ** 2).mean())
            self.num_inst += 1


@register(aliases=("ce",))
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            l = _as_np(label).ravel().astype("int64")
            p = _as_np(pred)
            assert l.shape[0] == p.shape[0]
            prob = p[_np.arange(l.shape[0]), l]
            self.sum_metric += (-_np.log(prob + self.eps)).sum()
            self.num_inst += l.shape[0]


@register(aliases=("nll_loss",))
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps, name, output_names, label_names)


@register(aliases=("pearsonr",))
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            l = _as_np(label).ravel()
            p = _as_np(pred).ravel()
            self.sum_metric += _np.corrcoef(p, l)[0, 1]
            self.num_inst += 1


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            l = _as_np(label).reshape(-1).astype("int64")
            p = _as_np(pred).reshape(-1, _as_np(pred).shape[-1])
            prob = p[_np.arange(l.shape[0]), l]
            if self.ignore_label is not None:
                ignore = (l == self.ignore_label)
                prob = prob * (1 - ignore) + ignore
                num -= ignore.sum()
            loss -= _np.log(_np.maximum(1e-10, prob)).sum()
            num += l.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        if isinstance(preds, NDArray):
            preds = [preds]
        for pred in preds:
            loss = _as_np(pred).sum()
            self.sum_metric += loss
            self.num_inst += _as_np(pred).size


class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name, output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            l = _as_np(label)
            p = _as_np(pred)
            reval = self._feval(l, p)
            if isinstance(reval, tuple):
                sum_metric, num_inst = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
