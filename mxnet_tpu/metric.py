"""Evaluation metrics (reference: python/mxnet/metric.py, 1,424 LoC).

Registry of EvalMetrics updated per batch.  Math is host-side numpy:
metrics are off the training hot path — outputs are already device
arrays and each update costs one ``asnumpy`` sync, like the reference's
``update_metric``.  Structure differs from the reference: batch
normalization (``_pairs``), binary confusion counting, and regression
error accumulation are shared helpers instead of per-class copies.
"""

from __future__ import annotations

import math

import numpy as _np

from .base import registry as _registry
from .ndarray import NDArray

_reg = _registry("metric")

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Perplexity",
           "Loss", "Torch", "Caffe", "CustomMetric", "np", "create",
           "register"]


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)


def check_label_shapes(labels, preds, shape=False):
    got = (labels.shape, preds.shape) if shape else (len(labels),
                                                    len(preds))
    if got[0] != got[1]:
        raise ValueError("Shape of labels {} does not match shape of "
                         "predictions {}".format(*got))


def _pairs(labels, preds, class_axis=None):
    """Normalize (labels, preds) to aligned numpy pairs; with
    ``class_axis`` set, probability tensors are argmaxed to class ids
    and both sides flatten to int32 vectors."""
    if isinstance(labels, NDArray):
        labels = [labels]
    if isinstance(preds, NDArray):
        preds = [preds]
    for label, pred in zip(labels, preds):
        l_np, p_np = _as_np(label), _as_np(pred)
        if class_axis is not None:
            # scores need an argmax exactly when they carry a class
            # axis the labels lack — element-count comparison also
            # covers (N, 1)-shaped label columns
            if p_np.ndim > 1 and p_np.size != l_np.size:
                p_np = p_np.argmax(axis=class_axis)
            l_np = l_np.astype("int32").reshape(-1)
            p_np = p_np.astype("int32").reshape(-1)
        yield l_np, p_np


class EvalMetric:
    """Accumulator protocol: ``update`` folds one batch into
    (sum_metric, num_inst); ``get`` reports sum/num."""

    def __init__(self, name, output_names=None, label_names=None,
                 **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    def get_config(self):
        return dict(self._kwargs, metric=type(self).__name__,
                    name=self.name, output_names=self.output_names,
                    label_names=self.label_names)

    def update_dict(self, label, pred):
        def pick(table, names):
            if names is None:
                return list(table.values())
            return [table[n] for n in names if n in table]
        self.update(pick(label, self.label_names),
                    pick(pred, self.output_names))

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    # -- resumable accumulator state (resilience subsystem) ----------------
    _STATE_SKIP = frozenset(["name", "output_names", "label_names",
                             "_kwargs"])

    def _is_plain(self, v, depth=0):
        if isinstance(v, (bool, int, float, str)) or v is None:
            return True
        if depth >= 4:
            return False
        if isinstance(v, (list, tuple)):
            return all(self._is_plain(x, depth + 1) for x in v)
        if isinstance(v, dict):
            return all(isinstance(k, (bool, int, float, str))
                       and self._is_plain(x, depth + 1)
                       for k, x in v.items())
        return False

    def state_dict(self):
        """Every plain-data accumulator attribute (num_inst,
        sum_metric, confusion counts, per-key tallies — anything a
        subclass accumulates in its ``__dict__``), excluding the
        construction config.  Generic on purpose: a subclass with a
        new counter is resumable without opting in.  Dict keys keep
        their types through ``TrainJobState``'s key-encoding layer."""
        state = {}
        for k, v in vars(self).items():
            if k in self._STATE_SKIP:
                continue
            if self._is_plain(v):
                state[k] = v
        return {"metric": type(self).__name__, "state": state}

    def load_state(self, st):
        if st.get("metric") != type(self).__name__:
            raise ValueError(
                "metric state was captured from %r but is being "
                "restored into %r" % (st.get("metric"),
                                      type(self).__name__))
        for k, v in st["state"].items():
            setattr(self, k, v)

    def get(self):
        value = (self.sum_metric / self.num_inst if self.num_inst
                 else float("nan"))
        return (self.name, value)

    def get_name_value(self):
        name, value = self.get()
        names = name if isinstance(name, list) else [name]
        values = value if isinstance(value, list) else [value]
        return list(zip(names, values))


def register(klass=None, name=None, aliases=()):
    if klass is None:
        return lambda k: register(k, name, aliases)
    _reg.register(klass, name=name, aliases=aliases)
    return klass


def create(metric, *args, **kwargs):
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    return _reg.get(metric)(*args, **kwargs)


@register
class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def state_dict(self):
        st = super().state_dict()
        st["children"] = [m.state_dict() for m in self.metrics]
        return st

    def load_state(self, st):
        children = st.get("children") or []
        if len(children) != len(self.metrics):
            raise ValueError(
                "composite metric state has %d children, metric has %d"
                % (len(children), len(self.metrics)))
        super().load_state(st)
        for m, child in zip(self.metrics, children):
            m.load_state(child)

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            names += name if isinstance(name, list) else [name]
            values += value if isinstance(value, (list, tuple)) \
                else [value]
        return (names, values)


@register(aliases=("acc",))
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes([labels] if isinstance(labels, NDArray)
                           else labels,
                           [preds] if isinstance(preds, NDArray)
                           else preds)
        for l, p in _pairs(labels, preds, class_axis=self.axis):
            self.sum_metric += int((p == l).sum())
            self.num_inst += l.size


@register(aliases=("top_k_accuracy", "top_k_acc"))
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        self.top_k = top_k
        self.name += "_%d" % top_k

    def update(self, labels, preds):
        for l, p in _pairs(labels, preds):
            l = l.astype("int32").reshape(-1)
            if p.ndim == 1:
                self.sum_metric += int((p.astype("int32") == l).sum())
            else:
                # hits = label appears among the k largest scores
                top = _np.argpartition(p, -self.top_k,
                                       axis=-1)[:, -self.top_k:]
                self.sum_metric += int((top == l[:, None]).sum())
            self.num_inst += p.shape[0]


class _BinaryConfusion(EvalMetric):
    """Shared tp/fp/tn/fn accumulation for binary classifiers."""

    def reset(self):
        super().reset()
        self._tp = self._fp = self._tn = self._fn = 0.0

    def _count(self, labels, preds):
        for l, p in _pairs(labels, preds, class_axis=1):
            self._tp += int(((p == 1) & (l == 1)).sum())
            self._fp += int(((p == 1) & (l == 0)).sum())
            self._tn += int(((p == 0) & (l == 0)).sum())
            self._fn += int(((p == 0) & (l == 1)).sum())

    def _score(self):
        raise NotImplementedError

    def update(self, labels, preds):
        self._count(labels, preds)
        self.sum_metric = self._score()
        self.num_inst = 1


@register
class F1(_BinaryConfusion):
    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names)
        self.average = average

    def _score(self):
        precision = self._tp / max(self._tp + self._fp, 1e-12)
        recall = self._tp / max(self._tp + self._fn, 1e-12)
        return 2 * precision * recall / max(precision + recall, 1e-12)


@register
class MCC(_BinaryConfusion):
    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names)

    def _score(self):
        terms = ((self._tp + self._fp) * (self._tp + self._fn) *
                 (self._tn + self._fp) * (self._tn + self._fn))
        denom = math.sqrt(terms) if terms > 0 else 1.0
        return (self._tp * self._tn - self._fp * self._fn) / denom


class _Regression(EvalMetric):
    """Shared per-batch error accumulation for regression metrics."""

    @staticmethod
    def _error(l, p):
        raise NotImplementedError

    def update(self, labels, preds):
        for l, p in _pairs(labels, preds):
            if l.ndim == p.ndim - 1:
                l = l[..., None]
            self.sum_metric += float(self._error(l, p))
            self.num_inst += 1


@register
class MAE(_Regression):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    _error = staticmethod(lambda l, p: _np.abs(l - p).mean())


@register
class MSE(_Regression):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    _error = staticmethod(lambda l, p: ((l - p) ** 2).mean())


@register
class RMSE(_Regression):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    _error = staticmethod(
        lambda l, p: math.sqrt(((l - p) ** 2).mean()))


@register(aliases=("ce",))
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        for l, p in _pairs(labels, preds):
            ids = l.ravel().astype("int64")
            assert ids.shape[0] == p.shape[0]
            picked = p[_np.arange(ids.shape[0]), ids]
            self.sum_metric += float(-_np.log(picked + self.eps).sum())
            self.num_inst += ids.shape[0]


@register(aliases=("nll_loss",))
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps, name, output_names, label_names)


@register(aliases=("pearsonr",))
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for l, p in _pairs(labels, preds):
            self.sum_metric += float(
                _np.corrcoef(p.ravel(), l.ravel())[0, 1])
            self.num_inst += 1


@register
class Perplexity(EvalMetric):
    """exp of the mean NLL, with an optional ignored padding label."""

    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        for l, p in _pairs(labels, preds):
            ids = l.reshape(-1).astype("int64")
            flat = p.reshape(-1, p.shape[-1])
            picked = flat[_np.arange(ids.shape[0]), ids]
            n = ids.shape[0]
            if self.ignore_label is not None:
                pad = ids == self.ignore_label
                picked = _np.where(pad, 1.0, picked)
                n -= int(pad.sum())
            self.sum_metric += float(
                -_np.log(_np.maximum(1e-10, picked)).sum())
            self.num_inst += n

    def get(self):
        if not self.num_inst:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class Loss(EvalMetric):
    """Mean of raw loss outputs (no labels involved)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        if isinstance(preds, NDArray):
            preds = [preds]
        for pred in preds:
            p = _as_np(pred)
            self.sum_metric += float(p.sum())
            self.num_inst += p.size


class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    """Wrap a ``feval(label, pred) -> value | (sum, num)`` callable."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if "<" in name:
                name = "custom(%s)" % name
        super().__init__(name, output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for l, p in _pairs(labels, preds):
            result = self._feval(l, p)
            if isinstance(result, tuple):
                self.sum_metric += result[0]
                self.num_inst += result[1]
            else:
                self.sum_metric += result
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Lift a plain numpy feval into a CustomMetric (reference:
    metric.np)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
