"""ctypes binding for the native RecordIO reader
(src/io/recordio_reader.cc; reference: the C++ record readers in
src/io/iter_image_recordio_2.cc).

``NativeRecordReader`` mirrors MXRecordIO's read surface with the
framing/IO in C++; ``available()`` gates on the built library so pure-
Python environments fall back to mxnet_tpu.recordio transparently."""

from __future__ import annotations

import ctypes
import os

__all__ = ["available", "NativeRecordReader", "build_index"]

_LIB = None


def _lib():
    global _LIB
    if _LIB is not None:
        return _LIB
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "build", "librecordio_reader.so")
    if not os.path.exists(path):
        return None
    lib = ctypes.CDLL(path)
    lib.RIOGetLastError.restype = ctypes.c_char_p
    lib.RIOOpen.restype = ctypes.c_void_p
    lib.RIOOpen.argtypes = [ctypes.c_char_p]
    lib.RIOClose.argtypes = [ctypes.c_void_p]
    lib.RIOReset.argtypes = [ctypes.c_void_p]
    lib.RIOSeek.argtypes = [ctypes.c_void_p, ctypes.c_long]
    lib.RIOTell.restype = ctypes.c_long
    lib.RIOTell.argtypes = [ctypes.c_void_p]
    lib.RIONext.restype = ctypes.c_int
    lib.RIONext.argtypes = [ctypes.c_void_p,
                            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                            ctypes.POINTER(ctypes.c_uint64)]
    lib.RIOBuildIndex.restype = ctypes.c_long
    lib.RIOBuildIndex.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_uint64),
                                  ctypes.c_long]
    _LIB = lib
    return lib


def available():
    """True when the native library is built (make -C src/io)."""
    return _lib() is not None


class NativeRecordReader(object):
    """Sequential + seekable record reader over the native library."""

    def __init__(self, path):
        lib = _lib()
        if lib is None:
            raise RuntimeError(
                "native recordio reader not built; run `make -C src/io` "
                "or use mxnet_tpu.recordio.MXRecordIO")
        self._lib = lib
        self._h = lib.RIOOpen(path.encode())
        if not self._h:
            raise IOError(lib.RIOGetLastError().decode())

    def _handle(self):
        if not self._h:
            raise IOError("reader is closed")
        return self._h

    def read(self):
        """Next record bytes, or None at EOF."""
        h = self._handle()
        data = ctypes.POINTER(ctypes.c_uint8)()
        size = ctypes.c_uint64()
        rc = self._lib.RIONext(h, ctypes.byref(data), ctypes.byref(size))
        if rc == 0:
            return None
        if rc < 0:
            raise IOError(self._lib.RIOGetLastError().decode())
        return ctypes.string_at(data, size.value)

    def seek(self, offset):
        """Position at a byte *offset* (record boundary)."""
        if self._lib.RIOSeek(self._handle(), offset) != 0:
            raise IOError("seek failed")

    def read_idx(self, offset):
        """Record at a byte *offset* (from the .idx file)."""
        self.seek(offset)
        return self.read()

    def reset(self):
        self._lib.RIOReset(self._handle())

    def tell(self):
        return self._lib.RIOTell(self._handle())

    def close(self):
        if self._h:
            self._lib.RIOClose(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def build_index(path):
    """Record start offsets for a .rec file (native full-file scan;
    reference: tools/im2rec index generation).  Grows the offset buffer
    in chunks so arbitrarily large files index completely."""
    lib = _lib()
    if lib is None:
        raise RuntimeError("native recordio reader not built")
    h = lib.RIOOpen(path.encode())
    if not h:
        raise IOError(lib.RIOGetLastError().decode())
    try:
        lib.RIOReset(h)
        out = []
        chunk = 1 << 16
        arr = (ctypes.c_uint64 * chunk)()
        while True:
            # scans forward from the current position, so repeated
            # calls with a bounded buffer index files of any size
            n = lib.RIOBuildIndex(h, arr, chunk)
            if n < 0:
                raise IOError(lib.RIOGetLastError().decode())
            out.extend(int(arr[i]) for i in range(n))
            if n < chunk:
                return out
    finally:
        lib.RIOClose(h)
