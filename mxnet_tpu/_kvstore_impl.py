"""KVStore implementations.

Reference: ``src/kvstore/`` — KVStoreLocal (kvstore_local.h), the comm layer
(comm.h), KVStoreNCCL (kvstore_nccl.h), KVStoreDist worker + server
(kvstore_dist.h / kvstore_dist_server.h over ps-lite ZeroMQ).

TPU-native mapping (SURVEY.md §5.8):
- 'local'/'device'  -> host-orchestrated multi-device sum/broadcast (the
  reference's CommCPU/CommDevice); used by Module/Trainer replicas.
- 'tpu'             -> XLA collectives over the device mesh (replaces both
  NCCL rings and the topology-tree planner; the ICI torus is XLA's job).
- 'dist_sync'/'dist_async' -> a host-side parameter-server over TCP
  (replaces ps-lite): sync mode aggregates pushes from all workers before
  applying the updater; async applies immediately; the optimizer can run
  server-side via set_optimizer exactly like kvstore_dist_server.h:346.
  Roles/addresses use the reference's DMLC_* env names so
  tools-launch-style localhost multi-process tests port directly.
- 2-bit gradient compression with error feedback rides the dist push path
  (gradient_compression.cc), computed per tensor and packed 4 lanes/byte
  on the wire.
"""

from __future__ import annotations

import json
import os
import pickle
import socket
import struct
import threading
import time

import numpy as _np

from . import ndarray as nd
from . import sanitizer as _san
from .ndarray import NDArray
from .base import MXNetError
from .observability import metrics as _metrics

__all__ = ["create", "KVStoreBase"]

# push/pull traffic instruments (module-level refs: these sit on the
# per-step gradient exchange path).  For the local store "bytes" is
# the logical value size moved through the aggregator; for the dist
# store it is what actually crosses the wire (compressed/rsp pushes
# count their packed size)
_PUSH_BYTES = _metrics.counter(
    "kvstore_push_bytes_total", "bytes pushed through kvstore")
_PULL_BYTES = _metrics.counter(
    "kvstore_pull_bytes_total", "bytes pulled through kvstore")


def _as_list(v):
    return v if isinstance(v, (list, tuple)) else [v]


def _value_bytes(arr):
    """Logical payload size of an NDArray/numpy value (metadata only —
    never forces a device sync)."""
    data = getattr(arr, "_data", arr)
    try:
        return int(getattr(data, "nbytes", 0))
    except (TypeError, ValueError):
        return 0     # exotic nbytes (mock/lazy proxy): skip accounting


class KVStoreBase:
    """Abstract API (reference: include/mxnet/kvstore.h:59-411)."""

    def __init__(self):
        self._updater = None
        self._compression = None

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def set_updater(self, updater):
        self._updater = updater

    def set_optimizer(self, optimizer):
        from . import optimizer as opt
        self.set_updater(opt.get_updater(optimizer))

    def set_gradient_compression(self, compression_params):
        self._compression = dict(compression_params or {})

    def init(self, key, value):
        raise NotImplementedError

    def push(self, key, value, priority=0):
        raise NotImplementedError

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        raise NotImplementedError

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        raise NotImplementedError

    def barrier(self):
        pass

    def get_optimizer_states(self, dump_optimizer=False):
        assert self._updater is not None, "updater is not set"
        return self._updater.get_states(dump_optimizer)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        from .resilience.checkpoint import atomic_write
        atomic_write(fname, self.get_optimizer_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "updater is not set"
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def _key_list(key, value):
    """Normalize (key, value) to ([keys], [[vals per key]])."""
    if isinstance(key, (str, int)):
        return [key], [_as_list(value)]
    assert len(key) == len(value)
    return list(key), [_as_list(v) for v in value]


def _str_key_index(table, key):
    """Deterministic insertion-order index for string keys (the reference
    maps str keys to ints the same way; Python's hash() is randomized per
    process and would break optimizer-state save/load and idx2name
    lookups).  Int keys pass through."""
    if isinstance(key, int):
        return key
    if key not in table:
        table[key] = len(table)
    return table[key]


class KVStoreLocal(KVStoreBase):
    """Single-process store with device reduction
    (reference: kvstore_local.h; comm.h Reduce/Broadcast)."""

    def __init__(self, name="local"):
        super().__init__()
        self.name = name
        self._store = {}
        self._str_idx = {}

    def _key_index(self, k):
        return _str_key_index(self._str_idx, k)

    @property
    def type(self):
        return self.name

    def init(self, key, value):
        keys, values = _key_list(key, value)
        for k, vs in zip(keys, values):
            if k in self._store:
                raise MXNetError("key %r already initialized" % (k,))
            self._store[k] = vs[0].copy() if isinstance(vs[0], NDArray) \
                else vs[0]

    def _reduce(self, vals):
        from .ndarray import sparse as _sp
        if len(vals) == 1:
            if isinstance(vals[0], _sp.BaseSparseNDArray):
                return vals[0]
            return vals[0].copy()
        if isinstance(vals[0], _sp.RowSparseNDArray):
            out = vals[0]
            for v in vals[1:]:
                out = _sp.sparse_add(out, v)
            return out
        total = vals[0].copy()
        for v in vals[1:]:
            total += v.as_in_context(total.context)
        return total

    def push(self, key, value, priority=0):
        from .ndarray import sparse as _sp
        keys, values = _key_list(key, value)
        for k, vs in zip(keys, values):
            merged = self._reduce(vs)
            _PUSH_BYTES.inc(_value_bytes(merged))
            if isinstance(merged, _sp.BaseSparseNDArray):
                merged = merged.todense()
            if self._updater is not None:
                self._updater(self._key_index(k), merged, self._store[k])
            else:
                # no updater: the merged value REPLACES the stored one
                # (reference kvstore_local.h PushImpl: ``local = merged``)
                stored = self._store[k]
                if isinstance(stored, _sp.BaseSparseNDArray):
                    self._store[k] = merged.tostype(stored.stype)
                else:
                    merged.as_in_context(stored.context).copyto(stored)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        from .ndarray import sparse as _sp
        keys, outs = _key_list(key, out)
        for k, os_ in zip(keys, outs):
            src = self._store[k]
            if isinstance(src, _sp.BaseSparseNDArray):
                src = src.todense()
            _PULL_BYTES.inc(_value_bytes(src) * len(os_))
            for o in os_:
                src.copyto(o)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only requested rows (reference: kvstore_local.h:244;
        row ids are deduplicated first like the reference's Unique pass —
        duplicate ids in a RowSparseNDArray would double-count under the
        gradient-sum todense semantics)."""
        from .ndarray import sparse as _sp
        keys, outs = _key_list(key, out)
        rids = _as_list(row_ids)
        for k, os_ in zip(keys, outs):
            src = self._store[k]
            if not isinstance(src, _sp.RowSparseNDArray):
                src = _sp.cast_storage(src, "row_sparse")
            for o, rid in zip(os_, rids * len(os_)):
                rid_np = _np.unique(_np.asarray(
                    rid.asnumpy() if isinstance(rid, NDArray) else rid,
                    _np.int64))
                retained = _sp.retain(src, nd.array(rid_np))
                o._data = retained._data
                o._aux = retained._aux
                o._shape = retained._shape
                o._stype = "row_sparse"


class KVStoreTPU(KVStoreLocal):
    """Mesh-collective store — push is an ICI all-reduce
    (replaces kvstore_nccl.h; reduction scheduled by XLA)."""

    def __init__(self, mesh=None):
        super().__init__("tpu")
        from .parallel import mesh as mesh_mod
        self.mesh = mesh or mesh_mod.make_mesh()

    def _reduce(self, vals):
        import jax
        from .ndarray import sparse as _sp
        if len(vals) == 1:
            return vals[0].copy()
        n = len(vals)
        devices = list(self.mesh.devices.flat)
        if n <= len(devices) and not any(
                isinstance(v, _sp.BaseSparseNDArray) for v in vals):
            # one replica per device: build a sharded stacked array in
            # place and psum it over ICI.  When the replica count is not
            # the dp extent, reduce over a dedicated 1-d sub-mesh of the
            # first n devices instead of falling back to the host loop.
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
            from .parallel import collectives
            if (len(self.mesh.shape) == 1
                    and self.mesh.shape.get("dp") == n):
                mesh, axis = self.mesh, "dp"
            else:
                # any other mesh layout (multi-axis, tp/pp-only, or a
                # replica count != the dp extent): reduce over a
                # dedicated 1-d sub-mesh of the first n devices
                mesh, axis = Mesh(_np.array(devices[:n]), ("kv",)), "kv"
            arrs = [v._data for v in vals]
            shards = [jax.device_put(a.reshape((1,) + a.shape), d)
                      for a, d in zip(arrs, mesh.devices.flat)]
            stacked = jax.make_array_from_single_device_arrays(
                (n,) + tuple(arrs[0].shape),
                NamedSharding(mesh, P(axis)), shards)
            summed = collectives.allreduce(stacked, mesh, axis)
            return NDArray(summed)
        return super()._reduce(vals)


# ---------------------------------------------------------------------------
# Distributed parameter server over TCP
# ---------------------------------------------------------------------------

_MSG_INIT = 0
_MSG_PUSH = 1
_MSG_PULL = 2
_MSG_BARRIER = 3
_MSG_CMD = 4
_MSG_STOP = 5
_MSG_SET_OPT = 6
_MSG_ROWPULL = 7
_MSG_HEARTBEAT = 8
_MSG_DEADQUERY = 9
_MSG_REPLY = 100

# ---------------------------------------------------------------------------
# Wire format: length-prefixed frames with JSON metadata and raw tensor
# sections — the analogue of ps-lite's zero-copy ZPush/ZPull
# (reference: kvstore_dist.h:161-169).  Tensor payloads travel as raw
# C-order bytes (no pickle: a network peer can at most hand us bytes to
# reinterpret as a numpy array, never code to run); control metadata is
# JSON.  The ONE exception is SET_OPT, whose body is a pickled optimizer
# exactly like the reference's set_optimizer — that call is rank-0
# control plane, not a tensor path, and the trust stance matches the
# reference's.
#
#   frame  := u64 body_len | body
#   body   := u8 kind | u32 meta_len | meta (UTF-8 JSON)
#             | u8 n_tensors | tensor*
#   tensor := u8 name_len | dtype name (ascii, numpy dtype .name)
#             | u8 ndim | u64 shape[ndim] | u64 nbytes | raw bytes
#
# dtype travels by numpy name ('float32', 'bfloat16', ...) so extension
# dtypes registered by ml_dtypes round-trip; endianness is native on
# both ends (homogeneous cluster assumption, same as ps-lite's).

_MAX_FRAME = 1 << 38  # 256 GiB sanity bound against corrupt streams


def _pack_tensor(arr):
    arr = _np.asarray(arr)
    shape = arr.shape  # BEFORE ascontiguousarray: it promotes 0-d to (1,)
    name = arr.dtype.name.encode("ascii")
    hdr = struct.pack("<B", len(name)) + name + struct.pack("<B", len(shape))
    if shape:
        hdr += struct.pack("<%dQ" % len(shape), *shape)
    hdr += struct.pack("<Q", arr.nbytes)
    # flat uint8 view: extension dtypes (bfloat16) don't implement the
    # buffer protocol, so memoryview(arr) would raise on them
    flat = _np.ascontiguousarray(arr).reshape(-1)
    return hdr, memoryview(flat.view(_np.uint8))


_COALESCE_BYTES = 1 << 16  # parts under this are copied+batched


def _send_frame(sock, kind, meta=None, tensors=()):
    meta_b = json.dumps(meta).encode() if meta else b"{}"
    parts = [struct.pack("<BI", kind, len(meta_b)), meta_b,
             struct.pack("<B", len(tensors))]
    for t in tensors:
        hdr, body = _pack_tensor(t)
        parts.append(hdr)
        parts.append(body)
    # coalesce the length prefix + small parts into single writes so a
    # control frame is ONE TCP segment (a write-write-read pattern would
    # hit Nagle + delayed-ACK ~40ms stalls); large tensor bodies still go
    # out zero-copy via their own sendall
    pending = bytearray(struct.pack(
        "<Q", sum(len(p) for p in parts)))
    for p in parts:
        if len(p) >= _COALESCE_BYTES:
            if pending:
                sock.sendall(pending)
                pending = bytearray()
            sock.sendall(p)
        else:
            pending += p
    if pending:
        sock.sendall(pending)


def _recv_exact(sock, n):
    buf = bytearray(n)
    mv = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(mv[got:], n - got)
        if not r:
            raise ConnectionError("peer closed")
        got += r
    return buf


def _recv_frame(sock):
    (n,) = struct.unpack("<Q", bytes(_recv_exact(sock, 8)))
    if n > _MAX_FRAME:
        raise ConnectionError("oversized frame (%d bytes)" % n)
    mv = memoryview(_recv_exact(sock, n))
    kind, meta_len = struct.unpack_from("<BI", mv, 0)
    off = 5
    meta = json.loads(bytes(mv[off:off + meta_len]).decode())
    off += meta_len
    (n_tensors,) = struct.unpack_from("<B", mv, off)
    off += 1
    tensors = []
    for _ in range(n_tensors):
        (name_len,) = struct.unpack_from("<B", mv, off)
        off += 1
        dtype = _np.dtype(bytes(mv[off:off + name_len]).decode("ascii"))
        off += name_len
        (ndim,) = struct.unpack_from("<B", mv, off)
        off += 1
        shape = struct.unpack_from("<%dQ" % ndim, mv, off) if ndim else ()
        off += 8 * ndim
        (nbytes,) = struct.unpack_from("<Q", mv, off)
        off += 8
        # views the frame buffer (writable bytearray) — no extra copy
        tensors.append(_np.frombuffer(mv[off:off + nbytes],
                                      dtype=dtype).reshape(shape))
        off += nbytes
    return kind, meta, tensors


def _connect_retry(host, port, deadline):
    """Connect with retry until *deadline*, a FRESH socket per attempt.

    Reusing one socket across attempts is not portable: after a
    ``connect`` fails with ECONNREFUSED (server still importing/binding),
    some kernels and sandboxes leave the fd permanently broken — every
    retry then fails with ECONNABORTED until the deadline, which is
    exactly the "worker never connects although the server came up 2s
    later" flakiness the dist drills showed.  A fresh socket per attempt
    connects on the first try once the server listens."""
    while True:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            sock.connect((host, port))
            return sock
        except (ConnectionRefusedError, OSError):
            sock.close()
            if time.time() > deadline:
                raise
            time.sleep(0.1)


def _rpc_call(sock, kind, meta=None, tensors=()):
    """Round-trip one request on *sock*; raises on an 'err' reply."""
    _send_frame(sock, kind, meta, tensors)
    rkind, rmeta, rtensors = _recv_frame(sock)
    if rkind != _MSG_REPLY:
        raise ConnectionError("protocol desync: reply kind %d" % rkind)
    if rmeta.get("status") != "ok":
        raise MXNetError("kvstore server error: %s" % rmeta.get("msg"))
    return rmeta, rtensors


class KVStoreServer:
    """Server process body (reference: kvstore_dist_server.h:155 —
    DataHandleEx:325, sync-mode ApplyUpdates:346, async immediate apply)."""

    def __init__(self, sync_mode, num_workers, host="127.0.0.1",
                 port=None, server_id=0):
        self.sync = sync_mode
        self.num_workers = num_workers
        self.server_id = int(server_id)
        self.store = {}
        self.pending = {}       # key -> [accum numpy, count]
        self._str_idx = {}      # deterministic string-key -> int index
        self.updater = None
        # barrier round-tracking by (round, worker rank) — robust to
        # overlapping rounds under worker skew, unlike a modulo counter
        self.barrier_rounds = {}   # round -> set of ranks arrived
        self.barrier_done = set()  # completed rounds (pruned)
        # heartbeat-based failure detection (reference: ps-lite
        # Postoffice::GetDeadNodes, kvstore_dist.h:119-128)
        self.heartbeats = {}       # node id -> last heartbeat walltime
        from .config import get_env as _get_env
        self.sync_timeout = _get_env("MXNET_KVSTORE_SYNC_TIMEOUT")
        self.cv = _san.condition(label="KVStoreServer.cv")
        self.lock = _san.rlock(label="KVStoreServer.lock")
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port or 0))
        self.port = self.sock.getsockname()[1]
        self.sock.listen(64)
        self._stop = _san.event()
        # Resolve handler-thread imports NOW, on the constructing thread.
        # The server may be started from the tail of mxnet_tpu/__init__.py
        # (DMLC_ROLE=server bootstrap) while the package is still marked
        # initializing; a ``from . import x`` in a handler thread would
        # deadlock on the package import lock.  The constructing thread
        # holds that lock reentrantly, so importing here is safe.
        from . import optimizer as _opt_mod
        from .ops import quantization as _quant_mod
        from . import profiler as _prof_mod
        self._opt_mod = _opt_mod
        self._quant_mod = _quant_mod
        self._prof_mod = _prof_mod
        # attributes conn-handler threads share; every one of these
        # must be consistently guarded (store/pending/heartbeats by
        # self.lock or self.cv; updater/sync rebinding by self.lock —
        # the SET_OPT/'mode' handlers race _apply's reads otherwise,
        # which is exactly what the lockset detector reports)
        _san.track(self, ("store", "pending", "updater", "sync",
                          "heartbeats", "barrier_rounds",
                          "barrier_done"), "KVStoreServer")

    def run(self):
        """Serve until a STOP message (reference: RunServer blocks the
        server process, python/mxnet/kvstore_server.py)."""
        threads = []
        self.sock.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = _san.thread(target=self._serve_conn, args=(conn,),
                            daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=1)

    def _apply(self, key, grad_np):
        grad = nd.array(grad_np)
        with self.lock:
            if key not in self.store:
                self.store[key] = grad.copy()
                return
            if self.updater is not None:
                self.updater(_str_key_index(self._str_idx, key), grad,
                             self.store[key])
            elif self.sync:
                # sync, no updater: the fully aggregated value replaces
                # the stored one (reference kvstore_dist_server.h: "if
                # no updater, just copy" — CopyFromTo(merged, &stored))
                grad.copyto(self.store[key])
            else:
                # async applies per-push; without an updater concurrent
                # workers would blindly overwrite each other (reference
                # asserts CHECK(updater_) on this path)
                raise MXNetError(
                    "dist_async push for key %r before an optimizer was "
                    "set — call kv.set_optimizer() first (async mode "
                    "requires the server-side updater)" % (key,))

    def _serve_conn(self, conn):
        try:
            while True:
                kind, meta, tensors = _recv_frame(conn)
                if kind == _MSG_STOP:
                    self._stop.set()
                    _send_frame(conn, _MSG_REPLY, {"status": "ok"})
                    return
                # every other message replies exactly once; ANY handler
                # exception becomes an 'err' reply instead of killing
                # this thread and leaving the worker blocked in recv
                try:
                    rmeta, rtensors = self._dispatch(kind, meta, tensors)
                except MXNetError as e:
                    rmeta, rtensors = {"status": "err", "msg": str(e)}, ()
                except Exception as e:
                    rmeta, rtensors = {"status": "err", "msg": "%s: %s"
                                       % (type(e).__name__, e)}, ()
                rmeta.setdefault("status", "ok")
                _send_frame(conn, _MSG_REPLY, rmeta, rtensors)
        except (ConnectionError, OSError):
            return

    def _dispatch(self, kind, meta, tensors):
        """Handle one request; returns (reply_meta, reply_tensors)."""
        if kind == _MSG_INIT:
            key = meta["key"]
            with self.lock:
                if key not in self.store:
                    self.store[key] = nd.array(tensors[0])
            return {}, ()
        if kind == _MSG_PUSH:
            key = meta["key"]
            if meta.get("compressed"):
                codes = self._quant_mod.unpack_2bit(
                    tensors[0], meta["n"]).astype(
                    _np.float32) * meta["threshold"]
                val = codes.reshape(meta["shape"])
            elif meta.get("rsp"):
                # row-sparse wire format: (row_ids, row values);
                # reconstruct dense for aggregation/updater
                # (reference: kvstore_dist_server.h DataHandleRowSparse)
                idx, vals = tensors
                dense = _np.zeros(tuple(meta["shape"]), vals.dtype)
                _np.add.at(dense, _np.asarray(idx, _np.int64), vals)
                val = dense
            else:
                val = tensors[0]
            # self.sync is rebound by the rank-0 'mode' command on a
            # DIFFERENT conn thread — unsynchronized, this read raced
            # the write (caught by the graftsan lockset detector); a
            # worker's first pushes could land on the wrong
            # consistency path
            with self.lock:
                sync = self.sync
            if sync:
                self._push_sync(key, val)
            else:
                self._apply(key, val)
            return {}, ()
        if kind == _MSG_PULL:
            with self.lock:
                arr = self.store[meta["key"]].asnumpy()
            return {}, (arr,)
        if kind == _MSG_ROWPULL:
            # server-side row retain: only the requested rows go on the
            # wire (reference: kvstore_dist_server.h row-sparse pull
            # path).  Out-of-range/negative ids return zero rows (retain
            # semantics) instead of wrapping.
            with self.lock:
                full = self.store[meta["key"]].asnumpy()
            ids = _np.asarray(tensors[0], _np.int64)
            valid = (ids >= 0) & (ids < full.shape[0])
            rows = full[_np.clip(ids, 0, full.shape[0] - 1)]
            rows[~valid] = 0
            return {}, (rows,)
        if kind == _MSG_BARRIER:
            self._barrier(meta.get("rank", 0), meta.get("round", 0))
            return {}, ()
        if kind == _MSG_HEARTBEAT:
            with self.lock:
                self.heartbeats[meta["node"]] = time.time()
            return {}, ()
        if kind == _MSG_DEADQUERY:
            now = time.time()
            with self.lock:
                dead = [n for n, ts in self.heartbeats.items()
                        if now - ts > meta["timeout"]]
            return {"dead": dead}, ()
        if kind == _MSG_SET_OPT:
            # control plane: optimizer ships pickled from rank 0, same
            # trust stance as the reference's set_optimizer.  The
            # rebinding must hold self.lock: _apply reads self.updater
            # under it from other conn threads (an unlocked write here
            # raced a concurrent async push — the lockset detector's
            # first real finding)
            optimizer = pickle.loads(tensors[0].tobytes())
            updater = self._opt_mod.get_updater(optimizer)
            with self.lock:
                self.updater = updater
            return {}, ()
        if kind == _MSG_CMD:
            # rank-0 command channel (reference: kvstore.h
            # SendCommandToServers:377); "mode" declares the consistency
            # model so one server binary serves both dist_sync and
            # dist_async launches; "profiler:*" drives this server
            # process's profiler (reference: kvstore.h:43-56)
            head = meta.get("head", "")
            body = meta.get("body")
            if head == "mode":
                with self.lock:
                    self.sync = "async" not in str(body)
            elif head == "profiler:set_config":
                cfg = dict(body)
                if "filename" in cfg and self.server_id:
                    # each server of a group writes its own trace
                    # (multi-server dumps must not clobber one file)
                    base, ext = os.path.splitext(cfg["filename"])
                    cfg["filename"] = "%s.server%d%s" % (
                        base, self.server_id, ext)
                self._prof_mod.set_config(**cfg)
            elif head == "profiler:set_state":
                self._prof_mod.set_state(str(body))
            elif head == "profiler:dump":
                self._prof_mod.dump(finished=bool(body))
            return {}, ()
        raise MXNetError("unknown kvstore message kind %d" % kind)

    def _push_sync(self, key, val):
        """Aggregate until all workers pushed, then apply once
        (reference: ApplyUpdates:346-358)."""
        with self.cv:
            if key in self.pending:
                self.pending[key][0] = self.pending[key][0] + val
                self.pending[key][1] += 1
            else:
                self.pending[key] = [val, 1]
            if self.pending[key][1] >= self.num_workers:
                acc = self.pending.pop(key)[0]
                self._apply(key, acc)
                self.cv.notify_all()
                return
            deadline = time.time() + self.sync_timeout
            while key in self.pending and time.time() < deadline:
                self.cv.wait(timeout=0.1)
            if key in self.pending:
                # drop the stale accumulator so a late worker cannot mix
                # gradients across rounds after the failure
                got = self.pending.pop(key)[1]
                self.cv.notify_all()
                raise MXNetError(
                    "dist_sync push for key %r timed out waiting for "
                    "%d workers (got %d) — worker desync or crash"
                    % (key, self.num_workers, got))

    def _barrier(self, rank, rnd):
        """Round-aware barrier: each worker reports (rank, its own round
        number); a round completes when every rank has arrived.  Immune
        to overlapping rounds under skew (a fast worker in round r+1
        cannot be miscounted into round r)."""
        with self.cv:
            if rnd in self.barrier_done:
                return
            arrived = self.barrier_rounds.setdefault(rnd, set())
            arrived.add(rank)
            if len(arrived) >= self.num_workers:
                self.barrier_done.add(rnd)
                del self.barrier_rounds[rnd]
                # prune: done rounds older than any pending round
                if len(self.barrier_done) > 1024:
                    keep = max(self.barrier_done)
                    self.barrier_done = {r for r in self.barrier_done
                                         if r > keep - 1024}
                self.cv.notify_all()
                return
            deadline = time.time() + self.sync_timeout
            while rnd not in self.barrier_done and time.time() < deadline:
                self.cv.wait(timeout=0.1)
            if rnd not in self.barrier_done:
                got = len(self.barrier_rounds.get(rnd, ()))
                raise MXNetError(
                    "kvstore barrier timed out: %d/%d workers arrived "
                    "for round %d" % (got, self.num_workers, rnd))


class KVStoreDist(KVStoreBase):
    """Worker side (reference: kvstore_dist.h:44 — ZPush/ZPull).

    Keys are sharded across ``DMLC_NUM_SERVER`` servers by stable hash,
    and arrays larger than ``MXNET_KVSTORE_BIGARRAY_BOUND`` elements are
    split into per-server chunks (reference: PSKV key/len caching,
    kvstore_dist.h:161-169 and the big-array sharding at :58).  A
    daemon heartbeat thread feeds server-side failure detection
    (num_dead_node); a restarted worker with the same rank reconnects
    statelessly (async-mode rejoin, reference is_recovery
    kvstore_dist.h:52)."""

    def __init__(self, name="dist_sync"):
        super().__init__()
        self.name = name
        host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
        self._rank = int(os.environ.get("DMLC_WORKER_RANK",
                                        os.environ.get("DMLC_RANK", "0")))
        self._num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        self._num_servers = int(os.environ.get("DMLC_NUM_SERVER", "1"))
        from .config import get_env as _get_env
        self._big_bound = _get_env("MXNET_KVSTORE_BIGARRAY_BOUND")
        # server s listens on root port + s (tools/launch.py convention)
        self._socks = []
        self._locks = []
        deadline = time.time() + _get_env("MXNET_KVSTORE_CONNECT_TIMEOUT")
        for s in range(self._num_servers):
            self._socks.append(_connect_retry(host, port + s, deadline))
            self._locks.append(_san.lock())
        self._residual = {}
        self._sharded_keys = set()
        self._barrier_round = 0
        # declare the consistency mode to every server (idempotent)
        for s in range(self._num_servers):
            self._rpc(_MSG_CMD, {"head": "mode", "body": name}, server=s)
        self._start_heartbeat()
        # register for profiler server-command routing (reference:
        # profiler.py set_kvstore_handle)
        from . import profiler as _prof
        _prof.set_kvstore_handle(self)

    def _start_heartbeat(self):
        from .config import get_env as _get_env
        interval = _get_env("MXNET_KVSTORE_HEARTBEAT_INTERVAL")
        node = "worker%d" % self._rank
        # dedicated sockets: heartbeats must not contend with bulk RPCs
        host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))

        def beat():
            socks = {}
            while not getattr(self, "_closed", False):
                for s in range(self._num_servers):
                    try:
                        if s not in socks:
                            hs = socket.socket(socket.AF_INET,
                                               socket.SOCK_STREAM)
                            hs.setsockopt(socket.IPPROTO_TCP,
                                          socket.TCP_NODELAY, 1)
                            hs.settimeout(5)
                            hs.connect((host, port + s))
                            socks[s] = hs
                        _rpc_call(socks[s], _MSG_HEARTBEAT,
                                  {"node": node})
                    except (ConnectionError, OSError):
                        # transient: server restarting; retry next beat
                        socks.pop(s, None)
                    except Exception as e:
                        # unexpected: surface at the next engine sync
                        # point (reference: exception chain rethrow)
                        from .runtime import engine as _engine
                        _engine.record_exception(e)
                        return
                time.sleep(interval)

        self._hb_thread = _san.thread(target=beat, daemon=True)
        self._hb_thread.start()

    def _server_for_key(self, k):
        import zlib
        return zlib.crc32(str(k).encode()) % self._num_servers

    def num_dead_node(self, node_id="all", timeout=60):
        """Count nodes whose heartbeat is older than *timeout* seconds
        (reference: kvstore_dist.h:119-128 get_num_dead_node)."""
        dead = self._rpc(_MSG_DEADQUERY, {"timeout": timeout},
                         server=0)[0]["dead"]
        if node_id == "all":
            return len(dead)
        return int(("worker%d" % node_id) in dead)

    @property
    def type(self):
        return self.name

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    def _rpc(self, kind, meta=None, tensors=(), server=None, key=None):
        """One framed round-trip; returns (reply_meta, reply_tensors)."""
        s = (server if server is not None
             else self._server_for_key(key) if key is not None else 0)
        with self._locks[s]:
            reply = _rpc_call(self._socks[s], kind, meta, tensors)
        # wire-level traffic accounting (payload bytes, post
        # compression/rsp packing — the number a capacity planner
        # multiplies by worker count)
        if kind == _MSG_PUSH and tensors:
            _PUSH_BYTES.inc(sum(int(getattr(t, "nbytes", 0))
                                for t in tensors))
        elif kind in (_MSG_PULL, _MSG_ROWPULL) and reply[1]:
            _PULL_BYTES.inc(sum(int(getattr(t, "nbytes", 0))
                                for t in reply[1]))
        return reply

    def _rpc_fanout(self, calls):
        """Round-trip one request per server CONCURRENTLY — sharded
        keys touch every server, and N sequential TCP round trips would
        serialize what ps-lite pipelines (kvstore_dist.h ZPush over
        per-server channels).  calls: [(server, kind, meta, tensors)];
        returns replies in call order.

        Daemon threads rather than a ThreadPoolExecutor: the executor's
        atexit hook joins its (non-daemon) workers unconditionally, so a
        thread stuck in a timeout-less recv against a dead server would
        wedge process EXIT — with daemon threads a wedged fan-out can
        only block this call, exactly like the sequential code did."""
        if len(calls) <= 1:
            return [self._rpc(kind, meta, tensors, server=s)
                    for s, kind, meta, tensors in calls]
        results = [None] * len(calls)
        errors = []

        def work(i, s, kind, meta, tensors):
            try:
                results[i] = self._rpc(kind, meta, tensors, server=s)
            except BaseException as e:  # surfaced on the caller thread
                errors.append(e)

        threads = [_san.thread(target=work, args=(i,) + c, daemon=True)
                   for i, c in enumerate(calls)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return results

    def _shard_splits(self, n):
        """Contiguous per-server chunk lengths for a flat size-n array."""
        base, rem = divmod(n, self._num_servers)
        return [base + (1 if i < rem else 0)
                for i in range(self._num_servers)]

    def init(self, key, value):
        from .ndarray import sparse as _sp
        keys, values = _key_list(key, value)
        for k, vs in zip(keys, values):
            arr = vs[0].asnumpy()
            # the sharding decision is taken ONCE at init and recorded:
            # later compression toggles must not change a key's layout
            # (every worker runs init, so every worker records it).
            # Sparse-typed keys are NEVER sharded: their pushes travel in
            # the compact row_sparse wire format to the hash-picked
            # server, which would silently miss the '#shard' keys — the
            # canonical big-embedding case would train on garbage.
            if (self._num_servers > 1 and arr.size > self._big_bound
                    and not self._compression
                    and not isinstance(vs[0], _sp.BaseSparseNDArray)):
                self._sharded_keys.add(k)
            if self._rank == 0:
                if k in self._sharded_keys:
                    flat = arr.ravel()
                    off = 0
                    for s, ln in enumerate(self._shard_splits(arr.size)):
                        self._rpc(_MSG_INIT,
                                  {"key": "%s#shard%d" % (k, s)},
                                  (flat[off:off + ln],), server=s)
                        off += ln
                else:
                    self._rpc(_MSG_INIT, {"key": k}, (arr,), key=k)
        self.barrier()

    def push(self, key, value, priority=0):
        keys, values = _key_list(key, value)
        for k, vs in zip(keys, values):
            total = vs[0]
            for v in vs[1:]:
                total = total + v
            from .ndarray import sparse as _sp
            if isinstance(total, _sp.RowSparseNDArray) and \
                    not self._compression and \
                    k not in self._sharded_keys:
                # compact wire format: only touched rows travel
                # (reference: kvstore_dist.h PushRowSparse).  A key that
                # was initialized dense AND sharded lives only as
                # '#shard' sub-keys, so its sparse gradients fall through
                # to the dense sharded path below.
                self._rpc(_MSG_PUSH,
                          {"key": k, "rsp": True,
                           "shape": [int(s) for s in total.shape]},
                          (_np.asarray(total._aux[0]),
                           _np.asarray(total._data)), key=k)
                continue
            if isinstance(total, _sp.BaseSparseNDArray):
                total = total.todense()
            arr = total.asnumpy()
            if k in self._sharded_keys:
                # big-array sharding: contiguous chunks pushed to every
                # server concurrently (reference: kvstore_dist.h:58
                # MXNET_KVSTORE_BIGARRAY_BOUND + ps-lite channels)
                flat = arr.ravel()
                calls = []
                off = 0
                for s, ln in enumerate(self._shard_splits(arr.size)):
                    calls.append((s, _MSG_PUSH,
                                  {"key": "%s#shard%d" % (k, s)},
                                  (flat[off:off + ln],)))
                    off += ln
                self._rpc_fanout(calls)
                continue
            meta = {"key": k}
            if self._compression and \
                    self._compression.get("type") == "2bit":
                from .ops.quantization import pack_2bit
                threshold = float(self._compression.get("threshold", 0.5))
                res = self._residual.get(k, _np.zeros_like(arr))
                acc = arr + res
                codes = _np.where(acc >= threshold, 1,
                                  _np.where(acc <= -threshold, -1, 0)) \
                    .astype(_np.int8)
                self._residual[k] = acc - codes * threshold
                packed, n_ = pack_2bit(codes)
                meta.update(compressed=True, threshold=threshold,
                            n=int(n_), shape=list(arr.shape))
                arr = packed
            self._rpc(_MSG_PUSH, meta, (arr,), key=k)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = _key_list(key, out)
        for k, os_ in zip(keys, outs):
            shape = tuple(int(s) for s in os_[0].shape)
            size = 1
            for s in shape:
                size *= s
            if k in self._sharded_keys:
                # pull every server's chunk concurrently, reassemble in
                # split order (same split rule as init/push)
                calls = [(s, _MSG_PULL,
                          {"key": "%s#shard%d" % (k, s)}, ())
                         for s, _ln in enumerate(
                             self._shard_splits(size))]
                replies = self._rpc_fanout(calls)
                arr = nd.array(_np.concatenate(
                    [r[1][0].ravel() for r in replies]).reshape(shape))
            else:
                arr = nd.array(
                    self._rpc(_MSG_PULL, {"key": k}, key=k)[1][0])
            for o in os_:
                arr.copyto(o)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        from .ndarray import sparse as _sp
        import jax.numpy as _jnp
        keys, outs = _key_list(key, out)
        rids = _as_list(row_ids)
        for k, os_ in zip(keys, outs):
            fetched = {}  # unique rid tuple -> rows, one RPC per set
            for o, rid in zip(os_, rids * len(os_)):
                rid_np = _np.unique(_np.asarray(
                    rid.asnumpy() if isinstance(rid, NDArray) else rid,
                    _np.int64))
                cache_key = rid_np.tobytes()
                if cache_key not in fetched:
                    # server-side retain: only requested rows come back
                    fetched[cache_key] = self._rpc(
                        _MSG_ROWPULL, {"key": k}, (rid_np,), key=k)[1][0]
                vals = fetched[cache_key]
                if isinstance(o, _sp.RowSparseNDArray):
                    o._data = _jnp.asarray(vals)
                    o._aux = [_jnp.asarray(rid_np.astype(_np.int32))]
                else:
                    full_shape = (o.shape if o.shape else None)
                    rsp = _sp.RowSparseNDArray(
                        nd.array(vals),
                        nd.array(rid_np.astype(_np.int32)),
                        full_shape)
                    o._data = rsp._data
                    o._aux = rsp._aux
                    o._shape = rsp._shape
                    o._stype = "row_sparse"

    def set_optimizer(self, optimizer):
        """Ship the optimizer to every server (reference: kvstore.py
        set_optimizer:450 pickles the optimizer to servers)."""
        if self._rank == 0:
            blob = _np.frombuffer(pickle.dumps(optimizer), _np.uint8)
            for s in range(self._num_servers):
                self._rpc(_MSG_SET_OPT, None, (blob,), server=s)
        self.barrier()

    def barrier(self):
        # server 0 coordinates; the round number makes overlapping
        # barriers under worker skew unambiguous
        self._barrier_round += 1
        self._rpc(_MSG_BARRIER,
                  {"rank": self._rank, "round": self._barrier_round},
                  server=0)

    def _send_command_to_servers(self, head, body):
        for s in range(self._num_servers):
            self._rpc(_MSG_CMD, {"head": head, "body": body}, server=s)

    def stop_server(self):
        self._closed = True
        from . import profiler as _prof
        if _prof._kvstore_handle is self:
            _prof.set_kvstore_handle(None)
        for s in range(self._num_servers):
            try:
                self._rpc(_MSG_STOP, server=s)
            except ConnectionError:
                pass


def create(name="local"):
    """Factory (reference: kvstore.cc:40-72 — contains 'dist' -> dist;
    'tpu'/'nccl' -> device collectives; else local)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if "dist" in name:
        if os.environ.get("DMLC_ROLE", "worker") == "server":
            raise MXNetError("server role should run "
                             "mxnet_tpu.kvstore_server.run_server()")
        return KVStoreDist(name)
    if name in ("tpu", "nccl"):
        return KVStoreTPU()
    if name in ("local", "device", "local_allreduce_cpu",
                "local_allreduce_device"):
        return KVStoreLocal(name)
    raise MXNetError("unknown kvstore type %r" % name)
