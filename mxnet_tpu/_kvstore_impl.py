def create(name="local"):
    raise NotImplementedError("kvstore backends land with the parallel milestone")
