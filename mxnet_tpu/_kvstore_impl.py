"""KVStore implementations.

Reference: ``src/kvstore/`` — KVStoreLocal (kvstore_local.h), the comm layer
(comm.h), KVStoreNCCL (kvstore_nccl.h), KVStoreDist worker + server
(kvstore_dist.h / kvstore_dist_server.h over ps-lite ZeroMQ).

TPU-native mapping (SURVEY.md §5.8):
- 'local'/'device'  -> host-orchestrated multi-device sum/broadcast (the
  reference's CommCPU/CommDevice); used by Module/Trainer replicas.
- 'tpu'             -> XLA collectives over the device mesh (replaces both
  NCCL rings and the topology-tree planner; the ICI torus is XLA's job).
- 'dist_sync'/'dist_async' -> a host-side parameter-server over TCP
  (replaces ps-lite): sync mode aggregates pushes from all workers before
  applying the updater; async applies immediately; the optimizer can run
  server-side via set_optimizer exactly like kvstore_dist_server.h:346.
  Roles/addresses use the reference's DMLC_* env names so
  tools-launch-style localhost multi-process tests port directly.
- 2-bit gradient compression with error feedback rides the dist push path
  (gradient_compression.cc), computed per tensor and packed 4 lanes/byte
  on the wire.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import socket
import struct
import threading
import time
from collections import OrderedDict as _OrderedDict

import numpy as _np

from . import ndarray as nd
from . import sanitizer as _san
from .ndarray import NDArray
from .base import MXNetError
from .observability import events as _obs_events
from .observability import metrics as _metrics
from .resilience import netchaos as _netchaos

__all__ = ["create", "KVStoreBase", "RPCTimeoutError", "SyncTimeoutError",
           "EvictedWorkerError"]

log = logging.getLogger(__name__)


class RPCTimeoutError(MXNetError):
    """A bulk KVStore RPC hit its per-call socket timeout
    (``MXNET_KVSTORE_RPC_TIMEOUT``) — the server died mid-reply or the
    network stalled.  The worker transport treats this as retryable:
    it reconnects and resends the SAME ``(rank, seq)`` request id, and
    the server's dedup window keeps the retried mutation
    exactly-once."""


class SyncTimeoutError(MXNetError):
    """A dist_sync push or barrier round expired with contributors
    still missing whose heartbeats are FRESH — an alive-but-slow
    straggler (provably-dead ranks are evicted instead, and the
    survivors proceed).  The message names the laggard rank(s)."""


class EvictedWorkerError(MXNetError):
    """A dist_sync contribution arrived from a worker that is not a
    CURRENT member of the expected-contributor set — it was evicted
    (heartbeat went provably stale and the surviving ranks completed
    rounds without it), retired by an operator ``kv.resize()``, or is
    a joiner that has not been admitted yet.  Silently merging such a
    gradient into a later round is exactly the stale-contributor
    corruption the membership epoch exists to kill, so the server
    rejects the push with this typed error instead; the worker must
    re-sync (pull current params through the reinit path, refresh its
    membership view) before contributing again — or exit cleanly if
    its rank was resized away."""

# push/pull traffic instruments (module-level refs: these sit on the
# per-step gradient exchange path).  For the local store "bytes" is
# the logical value size moved through the aggregator; for the dist
# store it is what actually crosses the wire (compressed/rsp pushes
# count their packed size)
_PUSH_BYTES = _metrics.counter(
    "kvstore_push_bytes_total", "bytes pushed through kvstore")
_PULL_BYTES = _metrics.counter(
    "kvstore_pull_bytes_total", "bytes pulled through kvstore")

# distributed fault-tolerance instruments (module-level refs — the
# RPC/heartbeat paths must not pay a registry lookup per call)
_RPC_RETRIES = _metrics.counter(
    "kvstore_rpc_retries_total",
    "bulk RPC transport retries (timeout/connection failure; the same "
    "request id is resent and deduped server-side)")
_HB_FAILURES = _metrics.counter(
    "kvstore_heartbeat_failures_total",
    "failed worker->server heartbeat attempts")
_SYNC_TIMEOUTS = _metrics.counter(
    "kvstore_sync_timeouts_total",
    "dist_sync push/barrier rounds that hit the sync deadline")
_EVICTIONS = _metrics.counter(
    "kvstore_evictions_total",
    "provably-dead ranks evicted from the expected-contributor set")
_DEDUP_HITS = _metrics.counter(
    "kvstore_dedup_hits_total",
    "duplicate mutating RPCs answered from the server dedup window "
    "instead of re-applied (exactly-once)")
_SERVER_RESTARTS = _metrics.counter(
    "kvstore_server_restarts_detected_total",
    "server restarts detected via a heartbeat epoch-token change")
_APPLIES = _metrics.counter(
    "kvstore_server_applies_total",
    "server-side state mutations (aggregated sync applies + async "
    "per-push applies + first-push creates)")
_ACTIVE_WORKERS = _metrics.gauge(
    "kvstore_active_workers",
    "workers currently admitted to the dist expected-contributor set "
    "(server-side live membership view; moves on evict/join/rejoin/"
    "resize)")
_STALE_REJECTS = _metrics.counter(
    "kvstore_stale_contributions_rejected_total",
    "sync pushes rejected with EvictedWorkerError because the pusher "
    "is not a current member (evicted/retired/unadmitted) or its "
    "membership view predates its eviction fence")

# after this many consecutive heartbeat failures to one server: one
# WARN (not a log line per beat) and a backed-off cadence
_HB_FAIL_WARN_AFTER = 3
_HB_BACKOFF = 5.0


def _as_list(v):
    return v if isinstance(v, (list, tuple)) else [v]


def _value_bytes(arr):
    """Logical payload size of an NDArray/numpy value (metadata only —
    never forces a device sync)."""
    data = getattr(arr, "_data", arr)
    try:
        return int(getattr(data, "nbytes", 0))
    except (TypeError, ValueError):
        return 0     # exotic nbytes (mock/lazy proxy): skip accounting


class KVStoreBase:
    """Abstract API (reference: include/mxnet/kvstore.h:59-411)."""

    def __init__(self):
        self._updater = None
        self._compression = None

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def set_updater(self, updater):
        self._updater = updater

    def set_optimizer(self, optimizer):
        from . import optimizer as opt
        self.set_updater(opt.get_updater(optimizer))

    def set_gradient_compression(self, compression_params):
        self._compression = dict(compression_params or {})

    def init(self, key, value):
        raise NotImplementedError

    def push(self, key, value, priority=0):
        raise NotImplementedError

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        raise NotImplementedError

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        raise NotImplementedError

    def barrier(self):
        pass

    def get_optimizer_states(self, dump_optimizer=False):
        assert self._updater is not None, "updater is not set"
        return self._updater.get_states(dump_optimizer)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        from .resilience.checkpoint import atomic_write
        atomic_write(fname, self.get_optimizer_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "updater is not set"
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def _key_list(key, value):
    """Normalize (key, value) to ([keys], [[vals per key]])."""
    if isinstance(key, (str, int)):
        return [key], [_as_list(value)]
    assert len(key) == len(value)
    return list(key), [_as_list(v) for v in value]


def _str_key_index(table, key):
    """Deterministic insertion-order index for string keys (the reference
    maps str keys to ints the same way; Python's hash() is randomized per
    process and would break optimizer-state save/load and idx2name
    lookups).  Int keys pass through."""
    if isinstance(key, int):
        return key
    if key not in table:
        table[key] = len(table)
    return table[key]


class KVStoreLocal(KVStoreBase):
    """Single-process store with device reduction
    (reference: kvstore_local.h; comm.h Reduce/Broadcast)."""

    def __init__(self, name="local"):
        super().__init__()
        self.name = name
        self._store = {}
        self._str_idx = {}

    def _key_index(self, k):
        return _str_key_index(self._str_idx, k)

    @property
    def type(self):
        return self.name

    def init(self, key, value):
        keys, values = _key_list(key, value)
        for k, vs in zip(keys, values):
            if k in self._store:
                raise MXNetError("key %r already initialized" % (k,))
            self._store[k] = vs[0].copy() if isinstance(vs[0], NDArray) \
                else vs[0]

    def _reduce(self, vals):
        from .ndarray import sparse as _sp
        if len(vals) == 1:
            if isinstance(vals[0], _sp.BaseSparseNDArray):
                return vals[0]
            return vals[0].copy()
        if isinstance(vals[0], _sp.RowSparseNDArray):
            out = vals[0]
            for v in vals[1:]:
                out = _sp.sparse_add(out, v)
            return out
        total = vals[0].copy()
        for v in vals[1:]:
            total += v.as_in_context(total.context)
        return total

    def push(self, key, value, priority=0):
        from .ndarray import sparse as _sp
        keys, values = _key_list(key, value)
        for k, vs in zip(keys, values):
            merged = self._reduce(vs)
            _PUSH_BYTES.inc(_value_bytes(merged))
            if isinstance(merged, _sp.BaseSparseNDArray):
                merged = merged.todense()
            if self._updater is not None:
                self._updater(self._key_index(k), merged, self._store[k])
            else:
                # no updater: the merged value REPLACES the stored one
                # (reference kvstore_local.h PushImpl: ``local = merged``)
                stored = self._store[k]
                if isinstance(stored, _sp.BaseSparseNDArray):
                    self._store[k] = merged.tostype(stored.stype)
                else:
                    merged.as_in_context(stored.context).copyto(stored)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        from .ndarray import sparse as _sp
        keys, outs = _key_list(key, out)
        for k, os_ in zip(keys, outs):
            src = self._store[k]
            if isinstance(src, _sp.BaseSparseNDArray):
                src = src.todense()
            _PULL_BYTES.inc(_value_bytes(src) * len(os_))
            for o in os_:
                src.copyto(o)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only requested rows (reference: kvstore_local.h:244;
        row ids are deduplicated first like the reference's Unique pass —
        duplicate ids in a RowSparseNDArray would double-count under the
        gradient-sum todense semantics)."""
        from .ndarray import sparse as _sp
        keys, outs = _key_list(key, out)
        rids = _as_list(row_ids)
        for k, os_ in zip(keys, outs):
            src = self._store[k]
            if not isinstance(src, _sp.RowSparseNDArray):
                src = _sp.cast_storage(src, "row_sparse")
            for o, rid in zip(os_, rids * len(os_)):
                rid_np = _np.unique(_np.asarray(
                    rid.asnumpy() if isinstance(rid, NDArray) else rid,
                    _np.int64))
                retained = _sp.retain(src, nd.array(rid_np))
                o._data = retained._data
                o._aux = retained._aux
                o._shape = retained._shape
                o._stype = "row_sparse"


class KVStoreTPU(KVStoreLocal):
    """Mesh-collective store — push is an ICI all-reduce
    (replaces kvstore_nccl.h; reduction scheduled by XLA)."""

    def __init__(self, mesh=None):
        super().__init__("tpu")
        from .parallel import mesh as mesh_mod
        self.mesh = mesh or mesh_mod.make_mesh()

    def _reduce(self, vals):
        import jax
        from .ndarray import sparse as _sp
        if len(vals) == 1:
            return vals[0].copy()
        n = len(vals)
        devices = list(self.mesh.devices.flat)
        if n <= len(devices) and not any(
                isinstance(v, _sp.BaseSparseNDArray) for v in vals):
            # one replica per device: build a sharded stacked array in
            # place and psum it over ICI.  When the replica count is not
            # the dp extent, reduce over a dedicated 1-d sub-mesh of the
            # first n devices instead of falling back to the host loop.
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
            from .parallel import collectives
            if (len(self.mesh.shape) == 1
                    and self.mesh.shape.get("dp") == n):
                mesh, axis = self.mesh, "dp"
            else:
                # any other mesh layout (multi-axis, tp/pp-only, or a
                # replica count != the dp extent): reduce over a
                # dedicated 1-d sub-mesh of the first n devices
                mesh, axis = Mesh(_np.array(devices[:n]), ("kv",)), "kv"
            arrs = [v._data for v in vals]
            shards = [jax.device_put(a.reshape((1,) + a.shape), d)
                      for a, d in zip(arrs, mesh.devices.flat)]
            stacked = jax.make_array_from_single_device_arrays(
                (n,) + tuple(arrs[0].shape),
                NamedSharding(mesh, P(axis)), shards)
            summed = collectives.allreduce(stacked, mesh, axis)
            return NDArray(summed)
        return super()._reduce(vals)


# ---------------------------------------------------------------------------
# Distributed parameter server over TCP
# ---------------------------------------------------------------------------

_MSG_INIT = 0
_MSG_PUSH = 1
_MSG_PULL = 2
_MSG_BARRIER = 3
_MSG_CMD = 4
_MSG_STOP = 5
_MSG_SET_OPT = 6
_MSG_ROWPULL = 7
_MSG_HEARTBEAT = 8
_MSG_DEADQUERY = 9
_MSG_REPLY = 100

# ---------------------------------------------------------------------------
# Wire format: length-prefixed frames with JSON metadata and raw tensor
# sections — the analogue of ps-lite's zero-copy ZPush/ZPull
# (reference: kvstore_dist.h:161-169).  Tensor payloads travel as raw
# C-order bytes (no pickle: a network peer can at most hand us bytes to
# reinterpret as a numpy array, never code to run); control metadata is
# JSON.  The ONE exception is SET_OPT, whose body is a pickled optimizer
# exactly like the reference's set_optimizer — that call is rank-0
# control plane, not a tensor path, and the trust stance matches the
# reference's.
#
#   frame  := u64 body_len | body
#   body   := u8 kind | u32 meta_len | meta (UTF-8 JSON)
#             | u8 n_tensors | tensor*
#   tensor := u8 name_len | dtype name (ascii, numpy dtype .name)
#             | u8 ndim | u64 shape[ndim] | u64 nbytes | raw bytes
#
# dtype travels by numpy name ('float32', 'bfloat16', ...) so extension
# dtypes registered by ml_dtypes round-trip; endianness is native on
# both ends (homogeneous cluster assumption, same as ps-lite's).

_MAX_FRAME = 1 << 38  # 256 GiB sanity bound against corrupt streams


def _pack_tensor(arr):
    arr = _np.asarray(arr)
    shape = arr.shape  # BEFORE ascontiguousarray: it promotes 0-d to (1,)
    name = arr.dtype.name.encode("ascii")
    hdr = struct.pack("<B", len(name)) + name + struct.pack("<B", len(shape))
    if shape:
        hdr += struct.pack("<%dQ" % len(shape), *shape)
    hdr += struct.pack("<Q", arr.nbytes)
    # flat uint8 view: extension dtypes (bfloat16) don't implement the
    # buffer protocol, so memoryview(arr) would raise on them
    flat = _np.ascontiguousarray(arr).reshape(-1)
    return hdr, memoryview(flat.view(_np.uint8))


_COALESCE_BYTES = 1 << 16  # parts under this are copied+batched


def _frame_parts(kind, meta, tensors):
    """The body parts of one wire frame (shared by the zero-copy
    sender and the netchaos torn-frame path — one wire format, two
    consumers, no drift)."""
    meta_b = json.dumps(meta).encode() if meta else b"{}"
    parts = [struct.pack("<BI", kind, len(meta_b)), meta_b,
             struct.pack("<B", len(tensors))]
    for t in tensors:
        hdr, body = _pack_tensor(t)
        parts.append(hdr)
        parts.append(body)
    return parts


def _frame_bytes(kind, meta=None, tensors=()):
    """One frame fully materialized (length prefix included) — used
    only by the torn-frame injections, never the hot path."""
    parts = _frame_parts(kind, meta, tensors)
    return (struct.pack("<Q", sum(len(p) for p in parts))
            + b"".join(bytes(p) for p in parts))


def _send_frame(sock, kind, meta=None, tensors=()):
    parts = _frame_parts(kind, meta, tensors)
    # coalesce the length prefix + small parts into single writes so a
    # control frame is ONE TCP segment (a write-write-read pattern would
    # hit Nagle + delayed-ACK ~40ms stalls); large tensor bodies still go
    # out zero-copy via their own sendall
    pending = bytearray(struct.pack(
        "<Q", sum(len(p) for p in parts)))
    for p in parts:
        if len(p) >= _COALESCE_BYTES:
            if pending:
                sock.sendall(pending)
                pending = bytearray()
            sock.sendall(p)
        else:
            pending += p
    if pending:
        sock.sendall(pending)


def _recv_exact(sock, n):
    buf = bytearray(n)
    mv = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(mv[got:], n - got)
        if not r:
            raise ConnectionError("peer closed")
        got += r
    return buf


def _recv_frame(sock):
    (n,) = struct.unpack("<Q", bytes(_recv_exact(sock, 8)))
    if n > _MAX_FRAME:
        raise ConnectionError("oversized frame (%d bytes)" % n)
    mv = memoryview(_recv_exact(sock, n))
    kind, meta_len = struct.unpack_from("<BI", mv, 0)
    off = 5
    meta = json.loads(bytes(mv[off:off + meta_len]).decode())
    off += meta_len
    (n_tensors,) = struct.unpack_from("<B", mv, off)
    off += 1
    tensors = []
    for _ in range(n_tensors):
        (name_len,) = struct.unpack_from("<B", mv, off)
        off += 1
        dtype = _np.dtype(bytes(mv[off:off + name_len]).decode("ascii"))
        off += name_len
        (ndim,) = struct.unpack_from("<B", mv, off)
        off += 1
        shape = struct.unpack_from("<%dQ" % ndim, mv, off) if ndim else ()
        off += 8 * ndim
        (nbytes,) = struct.unpack_from("<Q", mv, off)
        off += 8
        # views the frame buffer (writable bytearray) — no extra copy
        tensors.append(_np.frombuffer(mv[off:off + nbytes],
                                      dtype=dtype).reshape(shape))
        off += nbytes
    return kind, meta, tensors


def _connect_retry(host, port, deadline):
    """Connect with retry until *deadline* (a ``time.monotonic()``
    instant — wall-clock deadlines die to NTP steps, graftlint JG012),
    a FRESH socket per attempt.

    Reusing one socket across attempts is not portable: after a
    ``connect`` fails with ECONNREFUSED (server still importing/binding),
    some kernels and sandboxes leave the fd permanently broken — every
    retry then fails with ECONNABORTED until the deadline, which is
    exactly the "worker never connects although the server came up 2s
    later" flakiness the dist drills showed.  A fresh socket per attempt
    connects on the first try once the server listens."""
    while True:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            sock.connect((host, port))
            return sock
        except (ConnectionRefusedError, OSError):
            sock.close()
            if time.monotonic() > deadline:
                raise
            time.sleep(0.1)


def _rpc_call(sock, kind, meta=None, tensors=(), inject=False):
    """Round-trip one request on *sock*; raises on an 'err' reply.

    ``inject=True`` consults the netchaos worker-side fault points
    (the bulk data-plane RPCs of ``KVStoreDist``; control sockets and
    raw test callers opt out).  A socket timeout surfaces as the typed
    :class:`RPCTimeoutError` so callers can distinguish "server died
    mid-reply" from a server-reported error."""
    dup = False
    if inject:
        directives = _netchaos.on_worker_send(kind)
        if directives.get("torn"):
            payload = _frame_bytes(kind, meta, tensors)
            try:
                sock.sendall(payload[:max(9, len(payload) // 2)])
            finally:
                sock.close()
            raise ConnectionError("netchaos: torn request frame")
        dup = bool(directives.get("dup"))
    try:
        _send_frame(sock, kind, meta, tensors)
        if dup:
            # identical bytes, same request id: the server handles the
            # first copy and answers the second from its dedup window
            _send_frame(sock, kind, meta, tensors)
        rkind, rmeta, rtensors = _recv_frame(sock)
        if dup:
            rkind, rmeta, rtensors = _recv_frame(sock)
    except socket.timeout as exc:
        raise RPCTimeoutError(
            "kvstore RPC (kind %d) timed out after %.1fs waiting for "
            "the server's reply (MXNET_KVSTORE_RPC_TIMEOUT)"
            % (kind, sock.gettimeout() or -1.0)) from exc
    if rkind != _MSG_REPLY:
        raise ConnectionError("protocol desync: reply kind %d" % rkind)
    if rmeta.get("status") != "ok":
        if rmeta.get("code") == "sync_timeout":
            raise SyncTimeoutError(
                "kvstore server error: %s" % rmeta.get("msg"))
        if rmeta.get("code") == "evicted":
            raise EvictedWorkerError(
                "kvstore server error: %s" % rmeta.get("msg"))
        raise MXNetError("kvstore server error: %s" % rmeta.get("msg"))
    return rmeta, rtensors


def _node_rank(node):
    """The worker rank encoded in a heartbeat node id ('worker3' ->
    3); None for foreign node ids."""
    if isinstance(node, str) and node.startswith("worker"):
        try:
            return int(node[len("worker"):])
        except ValueError:
            return None
    return None


# mutating RPCs carry a ``(rank, seq, incarnation)`` request id (seq
# per-worker monotonic, incarnation per-process); the server's dedup
# window answers a retried id from cache so the mutation applies
# exactly once
_MUTATING_KINDS = frozenset((_MSG_INIT, _MSG_PUSH, _MSG_BARRIER,
                             _MSG_SET_OPT))
# data-plane kinds eligible for netchaos server-side reply faults
# (control/failure-detection traffic stays clean: injected heartbeat
# faults would just retest the heartbeat-failure counter)
_BULK_KINDS = frozenset((_MSG_INIT, _MSG_PUSH, _MSG_PULL, _MSG_ROWPULL,
                         _MSG_BARRIER, _MSG_SET_OPT, _MSG_CMD))


class _InFlight:
    """One dedup-window entry: the first arrival of a ``(rank, seq)``
    owns it and publishes the reply through ``event``; duplicates wait
    on the event and answer from ``result`` instead of re-applying."""

    __slots__ = ("event", "result")

    def __init__(self, done=False, result=None):
        self.event = _san.event()
        self.result = result
        if done:
            self.event.set()


class KVStoreServer:
    """Server process body (reference: kvstore_dist_server.h:155 —
    DataHandleEx:325, sync-mode ApplyUpdates:346, async immediate
    apply; ps-lite-grade fault tolerance: request-id dedup, heartbeat
    eviction, snapshot recovery — see docs/resilience.md)."""

    def __init__(self, sync_mode, num_workers, host="127.0.0.1",
                 port=None, server_id=0, snapshot_prefix=None):
        self.sync = sync_mode
        self.server_id = int(server_id)
        # -- live membership (elastic distributed training) -------------
        # The launch-time DMLC_NUM_WORKER is only the INITIAL world: the
        # expected-contributor set is versioned dynamic state.  Every
        # change (evict / join / rejoin / operator resize) bumps
        # ``membership_epoch``, which rides every heartbeat and sync
        # reply so workers re-shard at the next batch boundary.
        self.world = int(num_workers)     # operator-commanded target size
        self.joined = set(range(self.world))   # admitted members
        self.pending_join = set()   # heartbeating, admitted at a barrier
        self._rejoining = set()     # pending_join ranks that are rejoins
        self.pending_world = None   # resize target, applied at a barrier
        self.membership_epoch = 0
        # rank -> minimum membership epoch a sync push must declare:
        # set at eviction/retirement/admission so a push SENT before
        # the rank lost (or regained) membership can never merge into
        # a later round (the stale-contributor corruption)
        self.rank_fence = {}
        self.admitted_round = {r: 0 for r in range(self.world)}
        self.barrier_membership = {}   # completed round -> snapshot
        self.jobmeta = None    # opaque worker-published join metadata
        self.store = {}
        self.pending = {}       # key -> [accum, rank set, req-id set]
        # key -> ranks whose contribution was DROPPED when a sync
        # round was abandoned on timeout: their conn threads, still in
        # cv.wait, must raise too — 'key left pending' alone cannot
        # distinguish round-applied from round-abandoned, and an 'ok'
        # for a discarded gradient is exactly the silent failure this
        # subsystem exists to kill
        self.aborted_rounds = {}
        self._str_idx = {}      # deterministic string-key -> int index
        self.updater = None
        self._opt_blob = None   # pickled optimizer (snapshot restore)
        # barrier round-tracking by (round, worker rank) — robust to
        # overlapping rounds under worker skew, unlike a modulo counter
        self.barrier_rounds = {}   # round -> set of ranks arrived
        self.barrier_done = set()  # completed rounds (pruned)
        # heartbeat-based failure detection (reference: ps-lite
        # Postoffice::GetDeadNodes, kvstore_dist.h:119-128)
        self.heartbeats = {}       # node id -> last beat (monotonic)
        self.evicted = set()       # ranks removed from the expected set
        self.dedup = {}    # (rank, inc) -> OrderedDict(seq -> _InFlight)
        # request ids whose MUTATION is committed to the store but
        # whose reply is not yet sent: a snapshot taken inside the
        # apply must record them as done, or a post-restart retry of
        # the very push that triggered the snapshot double-applies
        self._applied_inflight = set()
        self.applies = 0           # state mutations (exactly-once proof)
        self.pushes_received = 0
        from .config import get_env as _get_env
        self.sync_timeout = _get_env("MXNET_KVSTORE_SYNC_TIMEOUT")
        self.evict_timeout = _get_env("MXNET_KVSTORE_EVICT_TIMEOUT")
        self.dedup_window = max(8, _get_env("MXNET_KVSTORE_DEDUP_WINDOW"))
        self.snapshot_every = _get_env("MXNET_KVSTORE_SNAPSHOT_EVERY")
        self.cv = _san.condition(label="KVStoreServer.cv")
        self.lock = _san.rlock(label="KVStoreServer.lock")
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port or 0))
        self.port = self.sock.getsockname()[1]
        self.sock.listen(64)
        self._stop = _san.event()
        # Resolve handler-thread imports NOW, on the constructing thread.
        # The server may be started from the tail of mxnet_tpu/__init__.py
        # (DMLC_ROLE=server bootstrap) while the package is still marked
        # initializing; a ``from . import x`` in a handler thread would
        # deadlock on the package import lock.  The constructing thread
        # holds that lock reentrantly, so importing here is safe.
        from . import optimizer as _opt_mod
        from .ops import quantization as _quant_mod
        from . import profiler as _prof_mod
        self._opt_mod = _opt_mod
        self._quant_mod = _quant_mod
        self._prof_mod = _prof_mod
        # epoch token: changes on every incarnation so workers detect a
        # restart through the heartbeat reply.  With a snapshot the
        # restored token + 1 keeps it monotonic; without one,
        # ms-resolution wall time makes a bounce distinguishable.
        self.epoch_token = int(time.time() * 1000) & 0x7FFFFFFFFFFF
        self._snap_seq = 0
        self._ckpt = None
        prefix = (snapshot_prefix if snapshot_prefix is not None
                  else _get_env("MXNET_KVSTORE_SNAPSHOT_PREFIX"))
        if prefix:
            if self.server_id:
                # each server of a group snapshots its own shard
                prefix = "%s-s%d" % (prefix, self.server_id)
            from .resilience.checkpoint import CheckpointManager
            # synchronous on purpose: the reply to a push must leave
            # AFTER the snapshot covering its apply is durable, or a
            # hard kill loses state a client was already told is
            # committed (and its dedup entry with it — the retried
            # push would then double-apply or, worse, never come)
            self._ckpt = CheckpointManager(prefix, keep_last=2,
                                           background=False)
            try:
                self._restore_snapshot()
            except Exception as exc:
                # a snapshot too corrupt for restore_latest's manifest
                # walk must not keep the parameter server down — start
                # fresh but say so loudly
                log.error("kvstore server %d: snapshot restore failed "
                          "(%s: %s); starting with an empty store",
                          self.server_id, type(exc).__name__, exc)
        # attributes conn-handler threads share; every one of these
        # must be consistently guarded (store/heartbeats/evicted/dedup/
        # applies by self.lock; pending/barrier_* by self.cv;
        # updater/sync rebinding by self.lock — the SET_OPT/'mode'
        # handlers race _apply's reads otherwise, which is exactly what
        # the lockset detector reports)
        _san.track(self, ("store", "pending", "updater", "sync",
                          "heartbeats", "barrier_rounds",
                          "barrier_done", "evicted", "dedup",
                          "applies", "pushes_received", "_opt_blob",
                          "_applied_inflight", "aborted_rounds",
                          "world", "joined", "pending_join",
                          "pending_world", "membership_epoch",
                          "rank_fence", "admitted_round", "jobmeta",
                          "_rejoining"),
                   "KVStoreServer")
        _ACTIVE_WORKERS.set(len(self.joined))

    @property
    def num_workers(self):
        """The CURRENT world size (operator-commanded target).  Kept
        as a property so legacy readers of the once-frozen constructor
        value see the live membership view; the expected-contributor
        set itself is :meth:`_expected_ranks`."""
        with self.lock:
            return self.world

    def _membership_snapshot(self):
        """One consistent view of the live membership (self.lock taken
        inside) — the payload attached to heartbeat and barrier
        replies and recorded per completed barrier round."""
        with self.lock:
            return {"mep": self.membership_epoch,
                    "members": sorted(self.joined),
                    "world": self.world}

    def _bump_membership_locked(self, action, ranks=(), **extra):
        """Callers hold self.lock already; it is an RLock, and taking
        it here keeps the write discipline lexically checkable.  One
        membership transition — bump the epoch, refresh the
        active-workers gauge, emit the ``membership`` event (old/new
        epoch + member list, the satellite contract)."""
        with self.lock:
            old = self.membership_epoch
            self.membership_epoch = old + 1
            members = sorted(self.joined)
            new = self.membership_epoch
            world = self.world
        _ACTIVE_WORKERS.set(len(members))
        _obs_events.emit("membership", action=action,
                         ranks=sorted(ranks), old_epoch=old,
                         new_epoch=new, members=members, world=world,
                         server=self.server_id, **extra)
        return old, new

    def run(self):
        """Serve until a STOP message (reference: RunServer blocks the
        server process, python/mxnet/kvstore_server.py)."""
        threads = []
        conns = []
        self.sock.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = _san.thread(target=self._serve_conn, args=(conn,),
                            daemon=True)
            t.start()
            threads.append(t)
            conns.append(conn)
        # shut every accepted connection so blocked conn threads wake
        # and peers see a dead server — a process kill closes these
        # fds implicitly; an in-process stop (tests, embedded servers)
        # must behave identically or workers keep heartbeating a ghost
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        for t in threads:
            t.join(timeout=1)

    def _apply(self, key, grad_np, applied_reqs=()):
        """Mutate the stored value; *applied_reqs* are the request ids
        whose mutation this apply commits (recorded so a snapshot
        taken right here already covers them — the reply hasn't been
        sent yet, but the state change is durable)."""
        grad = nd.array(grad_np)
        with self.lock:
            if key not in self.store:
                self.store[key] = grad.copy()
            elif self.updater is not None:
                self.updater(_str_key_index(self._str_idx, key), grad,
                             self.store[key])
            elif self.sync:
                # sync, no updater: the fully aggregated value replaces
                # the stored one (reference kvstore_dist_server.h: "if
                # no updater, just copy" — CopyFromTo(merged, &stored))
                grad.copyto(self.store[key])
            else:
                # async applies per-push; without an updater concurrent
                # workers would blindly overwrite each other (reference
                # asserts CHECK(updater_) on this path)
                raise MXNetError(
                    "dist_async push for key %r before an optimizer was "
                    "set — call kv.set_optimizer() first (async mode "
                    "requires the server-side updater)" % (key,))
            # counted AFTER the mutation branches: a push that raised
            # above mutated nothing, and bumping the exactly-once
            # proof counter for it breaks snapshot accounting (found
            # by graftsched's kvserver scenario — an owner push that
            # beat SET_OPT left applies == pushes despite applying 0)
            self.applies += 1
            _APPLIES.inc()
            if applied_reqs:
                self._applied_inflight.update(applied_reqs)
            self._maybe_snapshot()

    # -- state snapshots (recovery after a server kill) --------------------
    def _maybe_snapshot(self):
        """self.lock held.  Counter-based: every Nth apply commits a
        snapshot synchronously, so the caller's reply cannot leave
        before the state it acknowledges is durable."""
        if self._ckpt is None or self.snapshot_every <= 0:
            return
        if self.applies % self.snapshot_every:
            return
        self._snapshot_locked()

    def _snapshot_locked(self):
        # callers hold self.lock already; it is an RLock, and taking
        # it here keeps the write discipline lexically checkable
        with self.lock:
            self._snap_seq += 1
            return self._snapshot_body()

    def _snapshot_body(self):
        completed = {}
        for (rank, inc), per_client in self.dedup.items():
            seqs = [s for s, e in per_client.items()
                    if e.event.is_set()
                    or (rank, inc, s) in self._applied_inflight]
            if seqs:
                # the tail of the window is what a post-restart retry
                # can realistically replay
                completed["%d:%d" % (rank, inc)] = sorted(seqs)[-64:]
        snap_meta = {"epoch_token": self.epoch_token,
                     "applies": self.applies,
                     "str_idx": dict(self._str_idx),
                     "dedup": completed,
                     "evicted": sorted(self.evicted),
                     "world": self.world,
                     "joined": sorted(self.joined),
                     "membership_epoch": self.membership_epoch,
                     "rank_fence": {str(r): f for r, f in
                                    self.rank_fence.items()},
                     "admitted_round": {str(r): rnd for r, rnd in
                                        self.admitted_round.items()}}
        # store keys may be ints or strings; json round-trips both
        # exactly (a raw str(key) would fold 3 and "3" together)
        params = {json.dumps(k): v for k, v in self.store.items()}
        params[json.dumps("__kvmeta__")] = nd.array(_np.frombuffer(
            json.dumps(snap_meta).encode("utf-8"), _np.uint8).copy())
        states = None
        if self.updater is not None:
            states = pickle.dumps((self._opt_blob,
                                   self.updater.get_states(False)))
        self._ckpt.save_checkpoint(self._snap_seq, arg_params=params,
                                   optimizer_states=states)
        _obs_events.emit("kvstore", action="snapshot",
                         server=self.server_id, seq=self._snap_seq,
                         applies=self.applies, keys=len(self.store))

    def _restore_snapshot(self):
        """Restore the newest intact snapshot.  Runs on the
        constructor thread before any conn thread exists; the lock is
        held anyway so the write discipline is uniform."""
        rec = self._ckpt.restore_latest()
        if rec is None:
            return False
        from .ndarray import utils as nd_utils
        from .model import _split_save_dict
        arg_params, _aux = _split_save_dict(
            nd_utils.load(rec.params_path),
            context="kvstore snapshot %r" % rec.params_path)
        meta_arr = arg_params.pop(json.dumps("__kvmeta__"), None)
        snap_meta = {}
        if meta_arr is not None:
            snap_meta = json.loads(
                meta_arr.asnumpy().astype(_np.uint8).tobytes().decode(
                    "utf-8"))
        with self.lock:
            self.store = {json.loads(name): v
                          for name, v in arg_params.items()}
            self._str_idx = dict(snap_meta.get("str_idx") or {})
            self.applies = int(snap_meta.get("applies", 0))
            self.epoch_token = int(snap_meta.get(
                "epoch_token", self.epoch_token - 1)) + 1
            self.evicted = set(int(r)
                               for r in snap_meta.get("evicted", ()))
            if "world" in snap_meta:
                self.world = int(snap_meta["world"])
                self.joined = set(int(r)
                                  for r in snap_meta.get("joined", ()))
                self.membership_epoch = int(
                    snap_meta.get("membership_epoch", 0))
                self.rank_fence = {
                    int(r): int(f) for r, f in
                    (snap_meta.get("rank_fence") or {}).items()}
                self.admitted_round = {
                    int(r): int(rnd) for r, rnd in
                    (snap_meta.get("admitted_round") or {}).items()}
                _ACTIVE_WORKERS.set(len(self.joined))
            for client_s, seqs in (snap_meta.get("dedup") or {}).items():
                rank_s, _, inc_s = client_s.partition(":")
                client = (int(rank_s), int(inc_s or 0))
                per_client = self.dedup.setdefault(client,
                                                   _OrderedDict())
                for s in seqs:
                    per_client[int(s)] = _InFlight(
                        done=True, result=({"restored": True}, ()))
            if rec.states_path is not None:
                with open(rec.states_path, "rb") as f:
                    opt_blob, states = pickle.loads(f.read())
                self._opt_blob = opt_blob
                if opt_blob is not None:
                    self.updater = self._opt_mod.get_updater(
                        pickle.loads(opt_blob))
                    self.updater.set_states(states)
            self._snap_seq = max(self._ckpt.epochs() or [0])
        log.warning(
            "kvstore server %d: restored snapshot seq %d (%d keys, "
            "%d applies committed); epoch token now %d — workers will "
            "re-init anything newer than the snapshot",
            self.server_id, self._snap_seq, len(self.store),
            self.applies, self.epoch_token)
        _obs_events.emit("kvstore", action="restore",
                         server=self.server_id, seq=self._snap_seq,
                         keys=len(self.store), applies=self.applies,
                         epoch=self.epoch_token)
        return True

    def _serve_conn(self, conn):
        try:
            while True:
                kind, meta, tensors = _recv_frame(conn)
                if kind == _MSG_STOP:
                    self._stop.set()
                    _send_frame(conn, _MSG_REPLY, {"status": "ok"})
                    return
                # every other message replies exactly once; ANY handler
                # exception becomes an 'err' reply instead of killing
                # this thread and leaving the worker blocked in recv
                try:
                    rmeta, rtensors = self._handle(kind, meta, tensors)
                except SyncTimeoutError as e:
                    # typed on the wire: the worker re-raises the same
                    # class instead of a generic server error
                    rmeta, rtensors = {"status": "err",
                                       "code": "sync_timeout",
                                       "msg": str(e)}, ()
                except EvictedWorkerError as e:
                    rmeta, rtensors = {"status": "err",
                                       "code": "evicted",
                                       "msg": str(e)}, ()
                except MXNetError as e:
                    rmeta, rtensors = {"status": "err", "msg": str(e)}, ()
                except Exception as e:
                    rmeta, rtensors = {"status": "err", "msg": "%s: %s"
                                       % (type(e).__name__, e)}, ()
                rmeta.setdefault("status", "ok")
                if kind in (_MSG_PUSH, _MSG_BARRIER, _MSG_HEARTBEAT):
                    # the membership epoch rides EVERY heartbeat/sync
                    # reply so a worker notices a resize/evict/join
                    # within one sync round and re-shards at the batch
                    # boundary.  setdefault: a barrier reply already
                    # carries its completed round's CONSISTENT snapshot
                    # (mep + members together) — never mix in a newer
                    # epoch without its member list
                    if "mep" not in rmeta:
                        with self.lock:
                            rmeta["mep"] = self.membership_epoch
                if kind in _BULK_KINDS:
                    action = _netchaos.on_server_reply(kind)
                    if action == "drop":
                        # state already mutated; the worker's retried
                        # request id answers from the dedup window
                        continue
                    if action == "torn":
                        payload = _frame_bytes(_MSG_REPLY, rmeta,
                                               rtensors)
                        conn.sendall(payload[:max(9, len(payload) // 2)])
                        conn.close()
                        return
                _send_frame(conn, _MSG_REPLY, rmeta, rtensors)
        except (ConnectionError, OSError):
            return

    def _handle(self, kind, meta, tensors):
        """Dedup wrapper around :meth:`_dispatch`: the first arrival
        of a mutating ``(rank, seq)`` executes and caches its reply;
        duplicates (worker retries, netchaos dup injections) wait for
        the original and answer from cache — exactly-once."""
        req = meta.get("req") if isinstance(meta, dict) else None
        if req is None or kind not in _MUTATING_KINDS:
            return self._dispatch(kind, meta, tensors)
        rank, seq = int(req[0]), int(req[1])
        inc = int(req[2]) if len(req) > 2 else 0
        client = (rank, inc)
        with self.lock:
            per_client = self.dedup.get(client)
            if per_client is None:
                # a fresh incarnation of this rank: keep only a few
                # dead incarnations' windows around (their retries can
                # still arrive for a short while after a rejoin)
                stale = [c for c in self.dedup if c[0] == rank]
                if len(stale) >= 4:
                    self.dedup.pop(stale[0], None)
                per_client = self.dedup[client] = _OrderedDict()
            entry = per_client.get(seq)
            owner = entry is None
            if owner:
                entry = _InFlight()
                per_client[seq] = entry
                while len(per_client) > self.dedup_window:
                    oldest = next(iter(per_client))
                    if not per_client[oldest].event.is_set():
                        break       # never drop an in-flight entry
                    per_client.pop(oldest)
        if not owner:
            _DEDUP_HITS.inc()
            entry.event.wait(timeout=self.sync_timeout + 5.0)
            result = entry.result
            if result is None:
                raise MXNetError(
                    "duplicate request (%d, %d) whose original attempt "
                    "failed or is still in flight" % (rank, seq))
            rmeta = dict(result[0])
            rmeta["dup"] = True
            return rmeta, result[1]
        try:
            rmeta, rtensors = self._dispatch(kind, meta, tensors)
        except Exception:
            # no partial state survives a failed mutating RPC (sync
            # timeouts drop their accumulator), so let a future retry
            # re-execute instead of replaying the failure from cache
            with self.lock:
                self.dedup.get(client, {}).pop(seq, None)
                self._applied_inflight.discard((rank, inc, seq))
            entry.event.set()
            raise
        entry.result = (dict(rmeta), tuple(rtensors))
        entry.event.set()
        # the set event now records completion; the applied-in-flight
        # marker (set if a snapshot-covered apply ran) is redundant
        with self.lock:
            self._applied_inflight.discard((rank, inc, seq))
        return rmeta, rtensors

    def _dispatch(self, kind, meta, tensors):
        """Handle one request; returns (reply_meta, reply_tensors)."""
        if kind == _MSG_INIT:
            key = meta["key"]
            with self.lock:
                if key not in self.store:
                    self.store[key] = nd.array(tensors[0])
            return {}, ()
        if kind == _MSG_PUSH:
            _netchaos.on_server_push()   # hard-kill drill point
            key = meta["key"]
            with self.lock:
                self.pushes_received += 1
            if meta.get("compressed"):
                codes = self._quant_mod.unpack_2bit(
                    tensors[0], meta["n"]).astype(
                    _np.float32) * meta["threshold"]
                val = codes.reshape(meta["shape"])
            elif meta.get("rsp"):
                # row-sparse wire format: (row_ids, row values);
                # reconstruct dense for aggregation/updater
                # (reference: kvstore_dist_server.h DataHandleRowSparse)
                idx, vals = tensors
                dense = _np.zeros(tuple(meta["shape"]), vals.dtype)
                _np.add.at(dense, _np.asarray(idx, _np.int64), vals)
                val = dense
            else:
                val = tensors[0]
            # self.sync is rebound by the rank-0 'mode' command on a
            # DIFFERENT conn thread — unsynchronized, this read raced
            # the write (caught by the graftsan lockset detector); a
            # worker's first pushes could land on the wrong
            # consistency path
            with self.lock:
                sync = self.sync
            # the pusher's rank comes from the request id (every
            # KVStoreDist push carries one); raw legacy pushers may
            # declare it as meta['rank'] instead
            req = meta.get("req")
            req_id = None
            if req:
                rank = int(req[0])
                req_id = (rank, int(req[2]) if len(req) > 2 else 0,
                          int(req[1]))
            else:
                rank = int(meta.get("rank", 0))
            if sync:
                self._reject_stale_contributor(rank, meta.get("mep"),
                                               key)
                self._push_sync(key, val, rank, req_id)
            else:
                self._apply(key, val,
                            applied_reqs=(req_id,) if req_id else ())
            return {}, ()
        if kind == _MSG_PULL:
            with self.lock:
                arr = self.store[meta["key"]].asnumpy()
            return {}, (arr,)
        if kind == _MSG_ROWPULL:
            # server-side row retain: only the requested rows go on the
            # wire (reference: kvstore_dist_server.h row-sparse pull
            # path).  Out-of-range/negative ids return zero rows (retain
            # semantics) instead of wrapping.
            with self.lock:
                full = self.store[meta["key"]].asnumpy()
            ids = _np.asarray(tensors[0], _np.int64)
            valid = (ids >= 0) & (ids < full.shape[0])
            rows = full[_np.clip(ids, 0, full.shape[0] - 1)]
            rows[~valid] = 0
            return {}, (rows,)
        if kind == _MSG_BARRIER:
            snap = self._barrier(meta.get("rank", 0),
                                 meta.get("round", 0))
            # the completed round's membership snapshot rides the
            # reply: every waiter of round r receives the SAME
            # (epoch, members, world) triple, so all survivors apply
            # a resize/join/evict at the same batch boundary
            return dict(snap or {}), ()
        if kind == _MSG_HEARTBEAT:
            node = meta["node"]
            with self.lock:
                # monotonic: heartbeat staleness is an ELAPSED-time
                # comparison within this process — an NTP step must not
                # spuriously evict a healthy worker (graftlint JG012)
                self.heartbeats[node] = time.monotonic()
                # a fresh heartbeat from an evicted rank is a rejoin,
                # and one from an unknown rank inside the (possibly
                # pending-resize) world is a join — both become
                # join-PENDING: admission happens at the next barrier
                # completion, the only point with no sync push in
                # flight, so every survivor re-shards at the same
                # round boundary
                rank = _node_rank(node)
                unevicted = rank is not None and rank in self.evicted
                joining = False
                if unevicted:
                    self.evicted.discard(rank)
                    self.pending_join.add(rank)
                    self._rejoining.add(rank)
                elif (rank is not None
                        and rank not in self.joined
                        and rank not in self.pending_join):
                    # any heartbeating non-member is join-PENDING
                    # (visible in stats) — admission itself is gated
                    # by rank < world at the barrier boundary, so a
                    # rank beyond the (possibly pending-resize) world
                    # just waits for the operator to grow it in
                    self.pending_join.add(rank)
                    joining = True
                reply = {"epoch": self.epoch_token,
                         "mep": self.membership_epoch,
                         "members": sorted(self.joined),
                         "world": self.world}
            if unevicted:
                log.warning("kvstore server %d: rank %d heartbeating "
                            "again — rejoin pending (admitted at the "
                            "next sync-round boundary)",
                            self.server_id, rank)
                _obs_events.emit("kvstore", action="rejoin", rank=rank,
                                 server=self.server_id)
            elif joining:
                log.info("kvstore server %d: rank %d announced itself "
                         "— join pending admission", self.server_id,
                         rank)
            # the epoch token lets workers detect a server restart and
            # re-init only the keys the new incarnation lost
            return reply, ()
        if kind == _MSG_DEADQUERY:
            now = time.monotonic()
            with self.lock:
                dead = [n for n, ts in self.heartbeats.items()
                        if now - ts > meta["timeout"]]
                evicted = sorted(self.evicted)
            return {"dead": dead, "evicted": evicted}, ()
        if kind == _MSG_SET_OPT:
            # control plane: optimizer ships pickled from rank 0, same
            # trust stance as the reference's set_optimizer.  The
            # rebinding must hold self.lock: _apply reads self.updater
            # under it from other conn threads (an unlocked write here
            # raced a concurrent async push — the lockset detector's
            # first real finding)
            blob = tensors[0].tobytes()
            optimizer = pickle.loads(blob)
            updater = self._opt_mod.get_updater(optimizer)
            with self.lock:
                self.updater = updater
                self._opt_blob = blob   # snapshots re-create the updater
            return {}, ()
        if kind == _MSG_CMD:
            # rank-0 command channel (reference: kvstore.h
            # SendCommandToServers:377); "mode" declares the consistency
            # model so one server binary serves both dist_sync and
            # dist_async launches; "profiler:*" drives this server
            # process's profiler (reference: kvstore.h:43-56)
            head = meta.get("head", "")
            body = meta.get("body")
            if head == "mode":
                with self.lock:
                    self.sync = "async" not in str(body)
            elif head == "stats":
                # consistency/health introspection: restart detection
                # (which keys survived), exactly-once drills (applies),
                # eviction + live membership state — one locked
                # snapshot of the counters
                with self.lock:
                    return {"applies": self.applies,
                            "pushes": self.pushes_received,
                            "epoch": self.epoch_token,
                            "keys": sorted(self.store, key=repr),
                            "evicted": sorted(self.evicted),
                            "snapshots": self._snap_seq,
                            "server_id": self.server_id,
                            "mep": self.membership_epoch,
                            "members": sorted(self.joined),
                            "world": self.world,
                            "pending_world": self.pending_world,
                            "pending_join": sorted(self.pending_join),
                            "admitted_round":
                                {str(r): rnd for r, rnd in
                                 self.admitted_round.items()}}, ()
            elif head == "resize":
                # operator-commanded scale: N -> M in either direction
                # WITHOUT a restart.  Recorded as pending and applied
                # at the next barrier completion — the only instant a
                # dist_sync job provably has no push in flight — so
                # the transition lands on a batch boundary for every
                # worker at once.
                m = int(body)
                if m < 1:
                    raise MXNetError(
                        "resize target must be >= 1 worker, got %d" % m)
                with self.lock:
                    reply = {"world": self.world, "pending_world": m,
                             "mep": self.membership_epoch}
                    self.pending_world = m
                log.warning("kvstore server %d: operator resize to %d "
                            "worker(s) requested (world now %d); "
                            "applies at the next sync-round boundary",
                            self.server_id, m, reply["world"])
                _obs_events.emit("membership", action="resize_requested",
                                 world=reply["world"], target=m,
                                 server=self.server_id)
                return reply, ()
            elif head == "jobmeta":
                # opaque JSON blob the surviving workers publish (data
                # cursor, sampler state, round number): a mid-epoch
                # joiner fetches it to take over its shard assignment
                with self.lock:
                    self.jobmeta = body
            elif head == "jobmeta_get":
                with self.lock:
                    return {"meta": self.jobmeta}, ()
            elif head == "profiler:set_config":
                cfg = dict(body)
                if "filename" in cfg and self.server_id:
                    # each server of a group writes its own trace
                    # (multi-server dumps must not clobber one file)
                    base, ext = os.path.splitext(cfg["filename"])
                    cfg["filename"] = "%s.server%d%s" % (
                        base, self.server_id, ext)
                self._prof_mod.set_config(**cfg)
            elif head == "profiler:set_state":
                self._prof_mod.set_state(str(body))
            elif head == "profiler:dump":
                self._prof_mod.dump(finished=bool(body))
            return {}, ()
        raise MXNetError("unknown kvstore message kind %d" % kind)

    # -- straggler tolerance / live membership ------------------------------
    def _expected_ranks(self):
        """THE accessor for the ranks a sync round must hear from —
        the live membership view (self.lock taken inside; callers may
        hold self.cv — cv-before-lock is the one ordering this class
        uses).  Everything that used to derive an expected set or
        count from the frozen constructor ``num_workers`` routes
        through here (or :meth:`expected_count`)."""
        with self.lock:
            return set(self.joined)

    def expected_count(self):
        with self.lock:
            return len(self.joined)

    def _reject_stale_contributor(self, rank, mep, key):
        """A sync push from a non-member must fail TYPED, never merge
        into a later round (silent apply) or answer from the dedup
        cache: evicted ranks, ranks retired by a resize, and joiners
        not yet admitted all get :class:`EvictedWorkerError`.  The
        per-rank fence additionally rejects a push whose declared
        membership view predates the rank's own eviction — the push
        that was already on the wire when the round completed without
        it."""
        with self.lock:
            if rank in self.joined:
                fence = self.rank_fence.get(rank)
                if mep is None or fence is None or mep >= fence:
                    return
                reason = ("its membership view (epoch %d) predates its "
                          "eviction fence (epoch %d)" % (mep, fence))
            elif rank in self.pending_join and rank < self.world \
                    and mep is not None \
                    and mep >= self.rank_fence.get(rank, 0):
                # admit on first post-fence contribution: in a server
                # GROUP the barrier boundary lands a beat apart per
                # server, so a joiner admitted by server 0's round may
                # reach a sibling before that sibling's own barrier
                # completes.  The fence proves the pusher has already
                # observed a post-eviction membership view of THIS
                # server, so this is a fresh contribution, not a stale
                # one.
                self.joined.add(rank)
                self.pending_join.discard(rank)
                action = ("rejoin" if rank in self._rejoining
                          else "join")
                self._rejoining.discard(rank)
                self._bump_membership_locked(action, ranks=[rank],
                                             on_push=True)
                log.warning("kvstore server %d: admitted rank %d (%s) "
                            "on its first post-fence push",
                            self.server_id, rank, action)
                return
            elif rank in self.evicted:
                reason = "it was evicted from the expected set"
            elif rank >= self.world:
                reason = ("its rank was retired by an operator resize "
                          "to %d worker(s)" % self.world)
            else:
                reason = ("it has not been admitted yet (join pending "
                          "until the next sync-round boundary)")
            epoch = self.membership_epoch
        _STALE_REJECTS.inc()
        _obs_events.emit("membership", action="stale_reject", rank=rank,
                         key=str(key), epoch=epoch,
                         server=self.server_id)
        raise EvictedWorkerError(
            "sync push for key %r from rank %d rejected: %s "
            "(membership epoch %d) — re-sync params and refresh the "
            "membership view before contributing again"
            % (key, rank, reason, epoch))

    def _apply_membership_at_barrier(self, rnd):
        """self.cv held, called when barrier round *rnd* completes:
        apply every pending membership transition (operator resize,
        join/rejoin admissions).  A completed barrier is the one
        instant a dist_sync job provably has no push in flight — every
        worker's round-``rnd`` pushes returned before it arrived here —
        so the transition lands on the same batch boundary for all
        survivors, and the round's reply snapshot tells them about it."""
        with self.lock:
            resized = retired = None
            if self.pending_world is not None and \
                    self.pending_world != self.world:
                old_world, self.world = self.world, self.pending_world
                retired = sorted(r for r in self.joined
                                 if r >= self.world)
                for r in retired:
                    self.joined.discard(r)
                    self.rank_fence[r] = self.membership_epoch + 1
                self.pending_join = {r for r in self.pending_join
                                     if r < self.world}
                self.evicted = {r for r in self.evicted
                                if r < self.world}
                resized = old_world
            self.pending_world = None
            # only admit ranks whose heartbeat is FRESH: a retired/dead
            # process's last beats can leave a ghost pending entry, and
            # admitting it would stall rounds until it is re-evicted
            now = time.monotonic()
            stale = {r for r in self.pending_join
                     if r < self.world
                     and now - self.heartbeats.get("worker%d" % r,
                                                   -1e18)
                     > self.evict_timeout}
            self.pending_join -= stale
            self._rejoining -= stale
            admitted = sorted(r for r in self.pending_join
                              if r < self.world and r not in self.joined)
            for r in admitted:
                self.pending_join.discard(r)
                self.joined.add(r)
                self.admitted_round[r] = rnd
                # deliberately NOT re-fencing at admission: the fence
                # set at EVICTION time already rejects any push born
                # before the rank lost membership, while an
                # admission-epoch fence would falsely reject the
                # joiner's first post-admission push to a server whose
                # heartbeat reply it has not seen since the admission
                # bump (sub-second window in a server group)
            if resized is not None:
                old, new = self._bump_membership_locked(
                    "resize", ranks=retired, from_world=resized,
                    round=rnd)
                log.warning(
                    "kvstore server %d: resize %d -> %d applied at "
                    "round %d (membership epoch %d -> %d; retired "
                    "ranks %s)", self.server_id, resized, self.world,
                    rnd, old, new, retired)
            if admitted:
                rejoins = [r for r in admitted if r in self._rejoining]
                joins = [r for r in admitted if r not in self._rejoining]
                self._rejoining.difference_update(admitted)
                for action, ranks in (("rejoin", rejoins),
                                      ("join", joins)):
                    if not ranks:
                        continue
                    old, new = self._bump_membership_locked(
                        action, ranks=ranks, round=rnd)
                    log.warning(
                        "kvstore server %d: admitted rank(s) %s (%s) "
                        "at round %d (membership epoch %d -> %d; "
                        "expected contributors now %d)",
                        self.server_id, ranks, action, rnd, old, new,
                        len(self.joined))
        if resized is not None:
            # a shrink can complete rounds that were waiting on the
            # retired ranks — re-check everything pending (cv held)
            self._sweep_after_eviction()

    def _evict_dead(self, missing, context):
        """self.cv held.  Split *missing* ranks into provably-dead
        (heartbeat stale beyond the evict timeout — evicted, so the
        survivors make progress) and alive-but-slow laggards (the
        caller raises loudly, naming them)."""
        now = time.monotonic()
        evicted_now, laggards = [], []
        with self.lock:
            for r in sorted(missing):
                ts = self.heartbeats.get("worker%d" % r)
                if ts is not None and now - ts > self.evict_timeout:
                    self.evicted.add(r)
                    self.joined.discard(r)
                    self.pending_join.discard(r)
                    # any push of this rank's already on the wire was
                    # born before the eviction: fence it out until the
                    # rank observes a post-eviction membership view
                    self.rank_fence[r] = self.membership_epoch + 1
                    # the dead-node listing shrinks too: an evicted
                    # rank is no longer an expected cluster member
                    self.heartbeats.pop("worker%d" % r, None)
                    evicted_now.append(r)
                else:
                    laggards.append(r)
            if evicted_now:
                # eviction takes effect IMMEDIATELY (it is what
                # unblocks the waiting survivors), unlike join/resize
                # which defer to a barrier boundary
                self._bump_membership_locked("evict", ranks=evicted_now,
                                             reason=context)
            expected_now = len(self.joined)
        for r in evicted_now:
            _EVICTIONS.inc()
            log.warning(
                "kvstore server %d: evicted dead worker rank %d (%s; "
                "last heartbeat > %.1fs ago); expected contributors "
                "now %d", self.server_id, r, context,
                self.evict_timeout, expected_now)
            _obs_events.emit("kvstore", action="evict", rank=r,
                             server=self.server_id, reason=context)
        return evicted_now, laggards

    def _try_apply_pending(self, key):
        """self.cv held: apply *key*'s accumulator if every currently
        expected rank contributed; True when the round is finished."""
        acc = self.pending.get(key)
        if acc is None:
            return True
        expected = self._expected_ranks()
        if not expected or not expected <= acc[1]:
            return False
        self.pending.pop(key)
        # every contributor's request id is committed by this apply —
        # a snapshot inside it must cover the whole round
        self._apply(key, acc[0], applied_reqs=acc[2])
        self.cv.notify_all()
        return True

    def _try_complete_barrier(self, rnd):
        """self.cv held: complete barrier *rnd* if every currently
        expected rank arrived; True when the round is done."""
        if rnd in self.barrier_done:
            return True
        arrived = self.barrier_rounds.get(rnd)
        if arrived is None:
            return False
        expected = self._expected_ranks()
        if not expected or not expected <= arrived:
            return False
        self.barrier_done.add(rnd)
        del self.barrier_rounds[rnd]
        # the round boundary: apply pending membership transitions,
        # then record the round's consistent snapshot for its waiters
        self._apply_membership_at_barrier(rnd)
        self.barrier_membership[rnd] = self._membership_snapshot()
        # prune: done rounds older than any pending round
        if len(self.barrier_done) > 1024:
            keep = max(self.barrier_done)
            self.barrier_done = {r for r in self.barrier_done
                                 if r > keep - 1024}
            self.barrier_membership = {
                r: s for r, s in self.barrier_membership.items()
                if r in self.barrier_done}
        self.cv.notify_all()
        return True

    def _sweep_after_eviction(self):
        """self.cv held: an eviction shrank the expected set — every
        pending sync key and barrier round must be re-checked, not
        just the one whose deadline noticed the death."""
        for key in list(self.pending):
            self._try_apply_pending(key)
        for rnd in list(self.barrier_rounds):
            self._try_complete_barrier(rnd)

    def _push_sync(self, key, val, rank, req_id=None):
        """Aggregate until all expected workers pushed, then apply once
        (reference: ApplyUpdates:346-358).  On deadline expiry the
        heartbeat table decides: provably-dead ranks are evicted and
        the round completes for the survivors; an alive-but-slow
        laggard raises a loud typed error naming it."""
        with self.cv:
            if key in self.pending:
                self.pending[key][0] = self.pending[key][0] + val
                self.pending[key][1].add(rank)
                if req_id is not None:
                    self.pending[key][2].add(req_id)
            else:
                self.pending[key] = [val, {rank},
                                     {req_id} if req_id else set()]
            if self._try_apply_pending(key):
                return
            deadline = time.monotonic() + self.sync_timeout
            while key in self.pending and time.monotonic() < deadline:
                self.cv.wait(timeout=0.1)
            if key not in self.pending:
                self._raise_if_aborted(key, rank)
                return
            arrived = set(self.pending[key][1])
            missing = self._expected_ranks() - arrived
            evicted_now, laggards = self._evict_dead(
                missing, "sync push key=%r" % (key,))
            if evicted_now:
                self._sweep_after_eviction()
            if self._try_apply_pending(key):
                return
            # drop the stale accumulator so a late worker cannot mix
            # gradients across rounds after the failure; the OTHER
            # contributors still in cv.wait find their rank here and
            # raise the same typed error instead of a false 'ok'
            dropped = self.pending.pop(key)[1]
            got = len(dropped)
            dropped.discard(rank)      # this thread raises directly
            if dropped:
                self.aborted_rounds[key] = dropped
            self.cv.notify_all()
            _SYNC_TIMEOUTS.inc()
            _obs_events.emit("kvstore", action="sync_timeout",
                             key=str(key), got=got,
                             expected=self.expected_count(),
                             laggards=laggards, server=self.server_id)
            raise SyncTimeoutError(
                "dist_sync push for key %r timed out after %.1fs: got "
                "%d contributor(s), still waiting on alive-but-slow "
                "rank(s) %s — straggling worker, not a crash (dead "
                "ranks would have been evicted)"
                % (key, self.sync_timeout, got, laggards))

    def _raise_if_aborted(self, key, rank):
        """self.cv held: a waiter whose round vanished from pending
        checks whether it was APPLIED (fine — return ok) or ABANDONED
        with its gradient dropped (raise the same typed error the
        abandoning thread raised)."""
        aborted = self.aborted_rounds.get(key)
        if not aborted or rank not in aborted:
            return
        aborted.discard(rank)
        if not aborted:
            del self.aborted_rounds[key]
        raise SyncTimeoutError(
            "dist_sync push for key %r was abandoned after a sync "
            "timeout — rank %d's gradient was dropped with the round"
            % (key, rank))

    def _barrier(self, rank, rnd):
        """Round-aware barrier: each worker reports (rank, its own round
        number); a round completes when every expected rank has arrived.
        Immune to overlapping rounds under skew (a fast worker in round
        r+1 cannot be miscounted into round r); deadline expiry evicts
        provably-dead ranks exactly like :meth:`_push_sync`.  Returns
        the completed round's membership snapshot — the same
        (epoch, members, world) triple for every waiter of the round."""
        with self.cv:
            if rnd in self.barrier_done:
                return (self.barrier_membership.get(rnd)
                        or self._membership_snapshot())
            self.barrier_rounds.setdefault(rnd, set()).add(rank)
            if self._try_complete_barrier(rnd):
                return self.barrier_membership.get(rnd)
            deadline = time.monotonic() + self.sync_timeout
            while rnd not in self.barrier_done and \
                    time.monotonic() < deadline:
                self.cv.wait(timeout=0.1)
            if rnd in self.barrier_done:
                return (self.barrier_membership.get(rnd)
                        or self._membership_snapshot())
            arrived = set(self.barrier_rounds.get(rnd, ()))
            missing = self._expected_ranks() - arrived
            evicted_now, laggards = self._evict_dead(
                missing, "barrier round=%d" % rnd)
            if evicted_now:
                self._sweep_after_eviction()
            if self._try_complete_barrier(rnd):
                return self.barrier_membership.get(rnd)
            got = len(self.barrier_rounds.get(rnd, ()))
            expected = self.expected_count()
            _SYNC_TIMEOUTS.inc()
            _obs_events.emit("kvstore", action="barrier_timeout",
                             round=rnd, got=got, expected=expected,
                             laggards=laggards, server=self.server_id)
            raise SyncTimeoutError(
                "kvstore barrier timed out: %d/%d workers arrived for "
                "round %d; alive-but-slow rank(s): %s"
                % (got, expected, rnd, laggards))


class KVStoreDist(KVStoreBase):
    """Worker side (reference: kvstore_dist.h:44 — ZPush/ZPull).

    Keys are sharded across ``DMLC_NUM_SERVER`` servers by stable hash,
    and arrays larger than ``MXNET_KVSTORE_BIGARRAY_BOUND`` elements are
    split into per-server chunks (reference: PSKV key/len caching,
    kvstore_dist.h:161-169 and the big-array sharding at :58).  A
    daemon heartbeat thread feeds server-side failure detection
    (num_dead_node); a restarted worker with the same rank reconnects
    statelessly (async-mode rejoin, reference is_recovery
    kvstore_dist.h:52)."""

    def __init__(self, name="dist_sync"):
        super().__init__()
        self.name = name
        self._host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        self._root_port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
        self._rank = int(os.environ.get("DMLC_WORKER_RANK",
                                        os.environ.get("DMLC_RANK", "0")))
        self._num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        self._num_servers = int(os.environ.get("DMLC_NUM_SERVER", "1"))
        from .config import get_env as _get_env
        self._big_bound = _get_env("MXNET_KVSTORE_BIGARRAY_BOUND")
        self._rpc_timeout = _get_env("MXNET_KVSTORE_RPC_TIMEOUT")
        self._rpc_attempts = max(1, _get_env("MXNET_KVSTORE_RPC_RETRIES"))
        self._connect_timeout = _get_env("MXNET_KVSTORE_CONNECT_TIMEOUT")
        # mutating RPCs carry (rank, seq, incarnation): one id per
        # logical request, reused verbatim across transport retries.
        # The incarnation token distinguishes a RESTARTED worker with
        # the same rank (async rejoin, reference is_recovery) whose
        # fresh seq counter would otherwise collide with — and be
        # wrongly deduped against — its previous life's request ids.
        self._req_seq = 0
        self._incarnation = ((int(time.time() * 1000) << 16)
                             ^ os.getpid()) & 0x7FFFFFFFFFFF
        self._seq_lock = _san.lock(label="KVStoreDist.seq")
        # live membership view (elastic training): seeded from the
        # launch env, then updated from every heartbeat reply, every
        # sync reply's membership epoch, and each barrier's completed-
        # round snapshot.  ``num_workers`` reads THIS, never the
        # frozen env value.
        self._mview_lock = _san.lock(label="KVStoreDist.mview")
        self._mview = {"mep": 0,
                       "members": list(range(self._num_workers)),
                       "world": self._num_workers}
        # membership epochs are PER-SERVER counters: pushes declare
        # the last epoch seen from the server they go to (the fence
        # comparison must be same-server), while the partitioning
        # view above follows server 0 alone
        self._server_meps = {}
        # init-time values, kept so a restarted server's lost keys can
        # be re-initialized (only what the snapshot didn't cover)
        self._init_cache = {}
        self._cache_lock = _san.lock(label="KVStoreDist.init_cache")
        self._server_epochs = {}   # heartbeat thread only
        # server s listens on root port + s (tools/launch.py convention)
        self._socks = []
        self._locks = []
        for s in range(self._num_servers):
            self._socks.append(self._connect(s))
            self._locks.append(_san.lock())
        self._residual = {}
        self._sharded_keys = set()
        self._barrier_round = 0
        # declare the consistency mode to every server (idempotent)
        for s in range(self._num_servers):
            self._rpc(_MSG_CMD, {"head": "mode", "body": name}, server=s)
        self._start_heartbeat()
        # register for profiler server-command routing (reference:
        # profiler.py set_kvstore_handle)
        from . import profiler as _prof
        _prof.set_kvstore_handle(self)

    def _connect(self, s):
        """Fresh bulk-RPC socket to server *s*: connect-with-retry up
        to the connect deadline, then the per-call RPC timeout so a
        server dying mid-reply can never hang a worker in recv."""
        sock = _connect_retry(self._host, self._root_port + s,
                              time.monotonic() + self._connect_timeout)
        if self._rpc_timeout > 0:
            sock.settimeout(self._rpc_timeout)
        return sock

    def _start_heartbeat(self):
        from .config import get_env as _get_env
        interval = _get_env("MXNET_KVSTORE_HEARTBEAT_INTERVAL")
        node = "worker%d" % self._rank
        # dedicated sockets: heartbeats must not contend with bulk RPCs
        host, port = self._host, self._root_port

        def beat():
            socks = {}
            fails = {}   # server -> consecutive failures (bounded noise)
            defer = {}   # server -> monotonic time to retry after
            while not getattr(self, "_closed", False):
                for s in range(self._num_servers):
                    if time.monotonic() < defer.get(s, 0.0):
                        continue    # backed-off: THIS server only —
                        # healthy peers must keep seeing us at full
                        # cadence or they'd evict a live worker
                    try:
                        if s not in socks:
                            hs = socket.socket(socket.AF_INET,
                                               socket.SOCK_STREAM)
                            hs.setsockopt(socket.IPPROTO_TCP,
                                          socket.TCP_NODELAY, 1)
                            hs.settimeout(5)
                            hs.connect((host, port + s))
                            socks[s] = hs
                        rmeta, _ = _rpc_call(socks[s], _MSG_HEARTBEAT,
                                             {"node": node})
                    except (RPCTimeoutError, ConnectionError, OSError) \
                            as exc:
                        # transient (server restarting): retry next
                        # beat — but visibly and boundedly, not a
                        # silent forever-loop: count every failure,
                        # WARN once per outage, back off the cadence
                        hs = socks.pop(s, None)
                        if hs is not None:
                            try:
                                hs.close()
                            except OSError:
                                pass
                        _HB_FAILURES.inc()
                        fails[s] = fails.get(s, 0) + 1
                        if fails[s] == _HB_FAIL_WARN_AFTER:
                            log.warning(
                                "kvstore heartbeat to server %d failed "
                                "%d consecutive times (%s: %s); "
                                "failure detection degraded — backing "
                                "off to %.1fs between attempts",
                                s, fails[s], type(exc).__name__, exc,
                                interval * _HB_BACKOFF)
                        if fails[s] >= _HB_FAIL_WARN_AFTER:
                            defer[s] = (time.monotonic()
                                        + interval * _HB_BACKOFF)
                        continue
                    except Exception as e:
                        # unexpected: surface at the next engine sync
                        # point (reference: exception chain rethrow)
                        from .runtime import engine as _engine
                        _engine.record_exception(e)
                        return
                    if fails.get(s, 0) >= _HB_FAIL_WARN_AFTER:
                        log.info("kvstore heartbeat to server %d "
                                 "recovered after %d failures",
                                 s, fails[s])
                    fails[s] = 0
                    defer.pop(s, None)
                    # heartbeat replies carry the live membership
                    # snapshot (per-server epoch tracked for the push
                    # fence; server 0 is the partitioning authority)
                    self._update_mview(rmeta, server=s)
                    # restart detection: the server stamps every
                    # heartbeat reply with its incarnation's epoch token
                    epoch = rmeta.get("epoch")
                    if epoch is not None:
                        last = self._server_epochs.get(s)
                        self._server_epochs[s] = epoch
                        if last is not None and epoch != last:
                            self._on_server_restart(s, last, epoch)
                time.sleep(interval)

        self._hb_thread = _san.thread(target=beat, daemon=True)
        self._hb_thread.start()

    def _on_server_restart(self, s, old_epoch, new_epoch):
        """Heartbeat thread: server *s*'s epoch token changed — it
        restarted.  The re-init work runs on its OWN daemon thread:
        it issues blocking bulk RPCs (shared per-server socket locks),
        and stalling the beat loop on them would stop proving this
        worker's liveness to every OTHER server — long enough, the
        worker itself gets evicted as 'provably dead'."""
        _SERVER_RESTARTS.inc()
        log.warning("kvstore server %d restarted (epoch %s -> %s); "
                    "checking for lost keys", s, old_epoch, new_epoch)
        _obs_events.emit("kvstore", action="server_restart_detected",
                         server=s, old_epoch=old_epoch,
                         new_epoch=new_epoch, rank=self._rank)
        _san.thread(target=self._reinit_lost_keys, args=(s,),
                    daemon=True).start()

    def _reinit_lost_keys(self, s):
        """Re-init ONLY the keys restarted server *s* lost (a
        snapshot-restored server reports survivors in 'stats'), so
        rejoin pulls resume from committed state instead of zeros or
        KeyErrors.  Rank 0 holds the init-time cache (it is also the
        rank that sent the INITs originally); INIT is idempotent, so
        racing a concurrent snapshot-restored key is harmless."""
        try:
            have = set(self._rpc(_MSG_CMD, {"head": "stats"},
                                 server=s)[0].get("keys", ()))
            with self._cache_lock:
                cached = list(self._init_cache.items())
            sent = 0
            for k, arr in cached:
                for wire_key, value in self._wire_entries(k, arr, s):
                    if wire_key not in have:
                        self._rpc(_MSG_INIT, {"key": wire_key},
                                  (value,), server=s)
                        sent += 1
            if sent:
                log.warning(
                    "kvstore: re-initialized %d lost key(s) on "
                    "restarted server %d from their init-time values "
                    "(training state for those keys reset to init)",
                    sent, s)
                _obs_events.emit("kvstore", action="reinit", server=s,
                                 keys=sent, rank=self._rank)
        except (MXNetError, ConnectionError, OSError) as exc:
            # best effort from a daemon thread: a failed re-init must
            # not kill heartbeating — a later pull of a lost key will
            # fail loudly anyway
            log.warning("kvstore: re-init after server %d restart "
                        "failed (%s: %s)", s, type(exc).__name__, exc)

    def _wire_entries(self, k, arr, server):
        """(wire key, numpy value) pairs of key *k* that live on
        *server* — one per shard for sharded keys, the key itself when
        the stable hash picks this server."""
        if k in self._sharded_keys:
            flat = arr.ravel()
            off = 0
            for s2, ln in enumerate(self._shard_splits(arr.size)):
                if s2 == server:
                    yield "%s#shard%d" % (k, s2), flat[off:off + ln]
                off += ln
        elif self._server_for_key(k) == server:
            yield k, arr

    def _server_for_key(self, k):
        import zlib
        return zlib.crc32(str(k).encode()) % self._num_servers

    def num_dead_node(self, node_id="all", timeout=60):
        """Count nodes whose heartbeat is older than *timeout* seconds
        (reference: kvstore_dist.h:119-128 get_num_dead_node)."""
        dead = self._rpc(_MSG_DEADQUERY, {"timeout": timeout},
                         server=0)[0]["dead"]
        if node_id == "all":
            return len(dead)
        return int(("worker%d" % node_id) in dead)

    def server_stats(self, server=0):
        """One server's health/consistency counters: ``applies`` (the
        exactly-once proof), ``pushes``, ``epoch`` (incarnation
        token), ``keys``, ``evicted``, ``snapshots``."""
        return self._rpc(_MSG_CMD, {"head": "stats"}, server=server)[0]

    @property
    def type(self):
        return self.name

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        """The number of CURRENTLY active workers — the live
        membership view, not the launch-time ``DMLC_NUM_WORKER``
        (which only seeds it).  Moves on evict/join/rejoin/resize."""
        with self._mview_lock:
            return max(1, len(self._mview["members"]))

    # -- live membership (elastic training) ---------------------------------
    def _update_mview(self, rmeta, server=0):
        """Fold a reply's membership payload into the local view.
        Every server's epoch is tracked (pushes declare the last epoch
        seen from THAT server — the fence comparison is same-server);
        the partitioning view follows server 0, the authority.  A bare
        ``mep`` (sync replies) advances the epoch only; a full
        snapshot (heartbeat replies, barrier completed-round
        snapshots) replaces members/world atomically with its epoch —
        never mix a newer epoch with an older member list."""
        mep = rmeta.get("mep")
        if mep is None:
            return
        with self._mview_lock:
            if mep > self._server_meps.get(server, -1):
                self._server_meps[server] = int(mep)
            if server != 0:
                return
            if "members" in rmeta:
                if mep >= self._mview["mep"]:
                    self._mview = {
                        "mep": int(mep),
                        "members": [int(r) for r in rmeta["members"]],
                        "world": int(rmeta.get(
                            "world", self._mview["world"]))}
            elif mep > self._mview["mep"]:
                self._mview["mep"] = int(mep)

    def membership(self):
        """The worker's current view: ``{"mep", "members", "world"}``.
        After :meth:`barrier` this is the completed round's consistent
        server-0 snapshot — every worker of the round sees the same
        triple, so re-sharding decisions land on the same batch
        boundary everywhere."""
        with self._mview_lock:
            return {"mep": self._mview["mep"],
                    "members": list(self._mview["members"]),
                    "world": self._mview["world"]}

    def refresh_membership(self):
        """Force-refresh the view from server 0's stats (the
        authoritative membership for data partitioning) and return it."""
        st = self.server_stats(server=0)
        self._update_mview({"mep": st.get("mep", 0),
                            "members": st.get("members", []),
                            "world": st.get("world", 1)})
        return self.membership()

    def my_position(self):
        """This rank's index in the sorted member list (its shard
        assignment), or None when the rank is not currently a member
        (evicted / retired / not yet admitted)."""
        with self._mview_lock:
            members = sorted(self._mview["members"])
        try:
            return members.index(self._rank)
        except ValueError:
            return None

    def resize(self, world):
        """Operator-commanded rescale to *world* workers, in either
        direction, without a restart.  The target is recorded on every
        server and APPLIED at the next sync-round boundary (barrier
        completion): shrunk-away ranks see themselves retired in that
        round's membership snapshot and exit cleanly; grown slots are
        filled as new workers heartbeat in and are admitted.  Returns
        server 0's acknowledgement."""
        replies = [self._rpc(_MSG_CMD, {"head": "resize",
                                        "body": int(world)}, server=s)[0]
                   for s in range(self._num_servers)]
        _obs_events.emit("membership", action="resize_requested",
                         target=int(world), rank=self._rank)
        return replies[0]

    def put_job_meta(self, meta):
        """Publish the opaque job-state blob (JSON-able: data cursor,
        sampler state, round number) a mid-epoch joiner needs to take
        over its shard; kept on server 0."""
        self._rpc(_MSG_CMD, {"head": "jobmeta", "body": meta}, server=0)

    def get_job_meta(self):
        return self._rpc(_MSG_CMD, {"head": "jobmeta_get"},
                         server=0)[0].get("meta")

    def wait_admission(self, timeout=None, poll=None):
        """Block until this rank is ADMITTED to the expected set (a
        joiner/rejoiner becomes a member at a barrier completion), then
        align the local barrier-round counter with the round the server
        admitted it at — the joiner's next ``barrier()`` lands on the
        same round number as the survivors'.  Returns the refreshed
        membership view."""
        from .config import get_env as _get_env
        if timeout is None:
            timeout = _get_env("MXNET_KVSTORE_JOIN_TIMEOUT")
        if poll is None:
            poll = _get_env("MXNET_KVSTORE_ADMIT_POLL")
        deadline = time.monotonic() + timeout
        while True:
            st = self.server_stats(server=0)
            if self._rank in st.get("members", ()):
                self._update_mview({"mep": st.get("mep", 0),
                                    "members": st["members"],
                                    "world": st.get("world", 1)})
                admitted = (st.get("admitted_round") or {}).get(
                    str(self._rank))
                if admitted is not None:
                    self._barrier_round = int(admitted)
                _obs_events.emit("membership", action="admitted",
                                 rank=self._rank,
                                 round=self._barrier_round,
                                 mep=st.get("mep"))
                log.warning(
                    "kvstore rank %d admitted at round %d (membership "
                    "epoch %s, members %s)", self._rank,
                    self._barrier_round, st.get("mep"),
                    st.get("members"))
                return self.membership()
            if time.monotonic() > deadline:
                raise MXNetError(
                    "rank %d was not admitted within %.1fs "
                    "(members=%s, pending=%s, world=%s) — is a sync "
                    "round/barrier actually completing? admission "
                    "happens at barrier boundaries"
                    % (self._rank, timeout, st.get("members"),
                       st.get("pending_join"), st.get("world")))
            time.sleep(poll)

    def _rpc(self, kind, meta=None, tensors=(), server=None, key=None):
        """One framed round-trip; returns (reply_meta, reply_tensors).

        Mutating kinds get a ``(rank, seq)`` request id; every kind
        gets transport retries: a timeout or broken connection closes
        the socket, reconnects, and resends the SAME request — the
        server's dedup window makes retried mutations exactly-once."""
        s = (server if server is not None
             else self._server_for_key(key) if key is not None else 0)
        if kind in _MUTATING_KINDS:
            with self._seq_lock:
                self._req_seq += 1
                seq = self._req_seq
            meta = dict(meta or {})
            meta["req"] = [self._rank, seq, self._incarnation]
            if kind == _MSG_PUSH:
                # declare the membership view this contribution was
                # computed under (per-server epoch): the server's
                # per-rank fence uses it to reject a push born before
                # this rank's eviction
                with self._mview_lock:
                    meta["mep"] = self._server_meps.get(s, 0)
        with self._locks[s]:
            reply = self._rpc_with_retry(s, kind, meta, tensors)
        if isinstance(reply[0], dict):
            self._update_mview(reply[0], server=s)
        # wire-level traffic accounting (payload bytes, post
        # compression/rsp packing — the number a capacity planner
        # multiplies by worker count)
        if kind == _MSG_PUSH and tensors:
            _PUSH_BYTES.inc(sum(int(getattr(t, "nbytes", 0))
                                for t in tensors))
        elif kind in (_MSG_PULL, _MSG_ROWPULL) and reply[1]:
            _PULL_BYTES.inc(sum(int(getattr(t, "nbytes", 0))
                                for t in reply[1]))
        return reply

    def _rpc_with_retry(self, s, kind, meta, tensors):
        """self._locks[s] held.  One request id, up to
        ``MXNET_KVSTORE_RPC_RETRIES`` transport attempts with jittered
        backoff (resilience.retry).  Server-reported errors (MXNetError
        that is not a transport timeout) propagate immediately — only
        the transport retries, never the semantics."""
        def attempt():
            if self._socks[s] is None:
                self._socks[s] = self._connect(s)
            try:
                return _rpc_call(self._socks[s], kind, meta, tensors,
                                 inject=True)
            except (RPCTimeoutError, ConnectionError, OSError):
                # the stream is unusable (half-read reply, torn frame,
                # dead peer): drop it; the next attempt reconnects
                sock, self._socks[s] = self._socks[s], None
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                raise
        from .resilience.retry import retry_call
        return retry_call(
            attempt, attempts=self._rpc_attempts, base_delay=0.05,
            max_delay=1.0, jitter=0.5,
            retry_on=(RPCTimeoutError, ConnectionError, OSError),
            logger=log,
            on_retry=lambda _a, _e, _d: _RPC_RETRIES.inc())

    def _rpc_fanout(self, calls):
        """Round-trip one request per server CONCURRENTLY — sharded
        keys touch every server, and N sequential TCP round trips would
        serialize what ps-lite pipelines (kvstore_dist.h ZPush over
        per-server channels).  calls: [(server, kind, meta, tensors)];
        returns replies in call order.

        Daemon threads rather than a ThreadPoolExecutor: the executor's
        atexit hook joins its (non-daemon) workers unconditionally, so a
        thread stuck in a timeout-less recv against a dead server would
        wedge process EXIT — with daemon threads a wedged fan-out can
        only block this call, exactly like the sequential code did."""
        if len(calls) <= 1:
            return [self._rpc(kind, meta, tensors, server=s)
                    for s, kind, meta, tensors in calls]
        results = [None] * len(calls)
        errors = []

        def work(i, s, kind, meta, tensors):
            try:
                results[i] = self._rpc(kind, meta, tensors, server=s)
            except BaseException as e:  # surfaced on the caller thread
                errors.append(e)

        threads = [_san.thread(target=work, args=(i,) + c, daemon=True)
                   for i, c in enumerate(calls)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]
        return results

    def _shard_splits(self, n):
        """Contiguous per-server chunk lengths for a flat size-n array."""
        base, rem = divmod(n, self._num_servers)
        return [base + (1 if i < rem else 0)
                for i in range(self._num_servers)]

    def init(self, key, value):
        from .ndarray import sparse as _sp
        keys, values = _key_list(key, value)
        for k, vs in zip(keys, values):
            arr = vs[0].asnumpy()
            # only rank 0 caches the init-time values (it is the rank
            # that sends INITs, so restart re-init mirrors the same
            # authority) — the cache is a full host-side parameter
            # copy, and paying that on every worker would double host
            # memory for a restart-only path
            if self._rank == 0:
                with self._cache_lock:
                    self._init_cache[k] = arr
            # the sharding decision is taken ONCE at init and recorded:
            # later compression toggles must not change a key's layout
            # (every worker runs init, so every worker records it).
            # Sparse-typed keys are NEVER sharded: their pushes travel in
            # the compact row_sparse wire format to the hash-picked
            # server, which would silently miss the '#shard' keys — the
            # canonical big-embedding case would train on garbage.
            if (self._num_servers > 1 and arr.size > self._big_bound
                    and not self._compression
                    and not isinstance(vs[0], _sp.BaseSparseNDArray)):
                self._sharded_keys.add(k)
            if self._rank == 0:
                if k in self._sharded_keys:
                    flat = arr.ravel()
                    off = 0
                    for s, ln in enumerate(self._shard_splits(arr.size)):
                        self._rpc(_MSG_INIT,
                                  {"key": "%s#shard%d" % (k, s)},
                                  (flat[off:off + ln],), server=s)
                        off += ln
                else:
                    self._rpc(_MSG_INIT, {"key": k}, (arr,), key=k)
        self.barrier()

    def push(self, key, value, priority=0):
        keys, values = _key_list(key, value)
        for k, vs in zip(keys, values):
            total = vs[0]
            for v in vs[1:]:
                total = total + v
            from .ndarray import sparse as _sp
            if isinstance(total, _sp.RowSparseNDArray) and \
                    not self._compression and \
                    k not in self._sharded_keys:
                # compact wire format: only touched rows travel
                # (reference: kvstore_dist.h PushRowSparse).  A key that
                # was initialized dense AND sharded lives only as
                # '#shard' sub-keys, so its sparse gradients fall through
                # to the dense sharded path below.
                self._rpc(_MSG_PUSH,
                          {"key": k, "rsp": True,
                           "shape": [int(s) for s in total.shape]},
                          (_np.asarray(total._aux[0]),
                           _np.asarray(total._data)), key=k)
                continue
            if isinstance(total, _sp.BaseSparseNDArray):
                total = total.todense()
            arr = total.asnumpy()
            if k in self._sharded_keys:
                # big-array sharding: contiguous chunks pushed to every
                # server concurrently (reference: kvstore_dist.h:58
                # MXNET_KVSTORE_BIGARRAY_BOUND + ps-lite channels)
                flat = arr.ravel()
                calls = []
                off = 0
                for s, ln in enumerate(self._shard_splits(arr.size)):
                    calls.append((s, _MSG_PUSH,
                                  {"key": "%s#shard%d" % (k, s)},
                                  (flat[off:off + ln],)))
                    off += ln
                self._rpc_fanout(calls)
                continue
            meta = {"key": k}
            if self._compression and \
                    self._compression.get("type") == "2bit":
                from .ops.quantization import pack_2bit
                threshold = float(self._compression.get("threshold", 0.5))
                res = self._residual.get(k, _np.zeros_like(arr))
                acc = arr + res
                codes = _np.where(acc >= threshold, 1,
                                  _np.where(acc <= -threshold, -1, 0)) \
                    .astype(_np.int8)
                self._residual[k] = acc - codes * threshold
                packed, n_ = pack_2bit(codes)
                meta.update(compressed=True, threshold=threshold,
                            n=int(n_), shape=list(arr.shape))
                arr = packed
            self._rpc(_MSG_PUSH, meta, (arr,), key=k)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = _key_list(key, out)
        for k, os_ in zip(keys, outs):
            shape = tuple(int(s) for s in os_[0].shape)
            size = 1
            for s in shape:
                size *= s
            if k in self._sharded_keys:
                # pull every server's chunk concurrently, reassemble in
                # split order (same split rule as init/push)
                calls = [(s, _MSG_PULL,
                          {"key": "%s#shard%d" % (k, s)}, ())
                         for s, _ln in enumerate(
                             self._shard_splits(size))]
                replies = self._rpc_fanout(calls)
                arr = nd.array(_np.concatenate(
                    [r[1][0].ravel() for r in replies]).reshape(shape))
            else:
                arr = nd.array(
                    self._rpc(_MSG_PULL, {"key": k}, key=k)[1][0])
            for o in os_:
                arr.copyto(o)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        from .ndarray import sparse as _sp
        import jax.numpy as _jnp
        keys, outs = _key_list(key, out)
        rids = _as_list(row_ids)
        for k, os_ in zip(keys, outs):
            fetched = {}  # unique rid tuple -> rows, one RPC per set
            for o, rid in zip(os_, rids * len(os_)):
                rid_np = _np.unique(_np.asarray(
                    rid.asnumpy() if isinstance(rid, NDArray) else rid,
                    _np.int64))
                cache_key = rid_np.tobytes()
                if cache_key not in fetched:
                    # server-side retain: only requested rows come back
                    fetched[cache_key] = self._rpc(
                        _MSG_ROWPULL, {"key": k}, (rid_np,), key=k)[1][0]
                vals = fetched[cache_key]
                if isinstance(o, _sp.RowSparseNDArray):
                    o._data = _jnp.asarray(vals)
                    o._aux = [_jnp.asarray(rid_np.astype(_np.int32))]
                else:
                    full_shape = (o.shape if o.shape else None)
                    rsp = _sp.RowSparseNDArray(
                        nd.array(vals),
                        nd.array(rid_np.astype(_np.int32)),
                        full_shape)
                    o._data = rsp._data
                    o._aux = rsp._aux
                    o._shape = rsp._shape
                    o._stype = "row_sparse"

    def set_optimizer(self, optimizer):
        """Ship the optimizer to every server (reference: kvstore.py
        set_optimizer:450 pickles the optimizer to servers)."""
        if self._rank == 0:
            blob = _np.frombuffer(pickle.dumps(optimizer), _np.uint8)
            for s in range(self._num_servers):
                self._rpc(_MSG_SET_OPT, None, (blob,), server=s)
        self.barrier()

    def barrier(self):
        # every server coordinates its own copy of the round (the
        # round number makes overlapping barriers under worker skew
        # unambiguous): membership transitions apply at barrier
        # completion, and they must land on EVERY server at the same
        # round boundary or a resize would split one logical step's
        # expected sets across the key shards.  Server 0's completed-
        # round snapshot (folded into the view by _rpc) stays the
        # authoritative membership for data partitioning.
        self._barrier_round += 1
        meta = {"rank": self._rank, "round": self._barrier_round}
        if self._num_servers == 1:
            self._rpc(_MSG_BARRIER, meta, server=0)
        else:
            self._rpc_fanout([(s, _MSG_BARRIER, meta, ())
                              for s in range(self._num_servers)])

    def _send_command_to_servers(self, head, body):
        for s in range(self._num_servers):
            self._rpc(_MSG_CMD, {"head": head, "body": body}, server=s)

    def stop_server(self):
        self._closed = True
        from . import profiler as _prof
        if _prof._kvstore_handle is self:
            _prof.set_kvstore_handle(None)
        # deliberately NOT routed through the retry transport: a dead
        # server must not cost reconnect deadlines at shutdown — one
        # best-effort STOP per live socket
        for s in range(self._num_servers):
            try:
                with self._locks[s]:
                    sock = self._socks[s]
                    if sock is None:
                        continue
                    _rpc_call(sock, _MSG_STOP)
            except (RPCTimeoutError, ConnectionError, OSError):
                pass


def create(name="local"):
    """Factory (reference: kvstore.cc:40-72 — contains 'dist' -> dist;
    'tpu'/'nccl' -> device collectives; else local)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if "dist" in name:
        if os.environ.get("DMLC_ROLE", "worker") == "server":
            raise MXNetError("server role should run "
                             "mxnet_tpu.kvstore_server.run_server()")
        return KVStoreDist(name)
    if name in ("tpu", "nccl"):
        return KVStoreTPU()
    if name in ("local", "device", "local_allreduce_cpu",
                "local_allreduce_device"):
        return KVStoreLocal(name)
    raise MXNetError("unknown kvstore type %r" % name)
