"""Collective helpers.

Replaces the reference's comm layer (src/kvstore/comm.h Reduce/Broadcast,
kvstore_nccl.h ncclReduce/ncclBcast): on TPU collectives are XLA ops
(psum/all_gather/reduce_scatter/ppermute) emitted inside shard_map/pjit and
scheduled by the compiler onto ICI.  These wrappers exist so framework code
and user code share one vocabulary; inside a shard_map they are the raw
jax.lax collectives.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax.experimental.shard_map import shard_map

__all__ = ["allreduce", "allgather", "reduce_scatter", "broadcast",
           "psum", "pmean", "ppermute_ring", "axis_size"]

# in-shard_map primitives (axis_name bound by caller)
psum = jax.lax.psum
pmean = jax.lax.pmean


def axis_size(axis_name):
    """Static size of a mapped axis.  ``jax.lax.axis_size`` only exists
    in newer jax releases; ``psum(1, axis)`` is the classic idiom and
    constant-folds to a Python int, so callers can use the result in
    Python control flow either way."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


def ppermute_ring(x, axis_name, shift=1):
    """Rotate shards around the ring (ring-attention building block)."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


@functools.lru_cache(maxsize=None)
def _allreduce_fn(mesh, axis):
    @jax.jit
    def f(x):
        # x: (n, ...) sharded over axis on dim0 -> replicated sum over dim0
        def shard_fn(s):
            return jax.lax.psum(jnp.sum(s, axis=0), axis)
        return shard_map(shard_fn, mesh=mesh, in_specs=P(axis),
                         out_specs=P(), check_rep=False)(x)
    return f


def allreduce(stacked, mesh, axis="dp"):
    """Sum a leading-axis-sharded stack over *axis*; returns the
    replicated sum (shape = stacked.shape[1:]).  Host-callable."""
    return _allreduce_fn(mesh, axis)(stacked)


@functools.lru_cache(maxsize=None)
def _reduce_scatter_fn(mesh, axis):
    @jax.jit
    def f(x):
        # x: (n, m) sharded over axis -> (m,) sharded: device i holds the
        # i-th m/n block of the sum (ZeRO gradient layout)
        def shard_fn(s):
            return jax.lax.psum_scatter(s[0], axis, scatter_dimension=0,
                                        tiled=True)
        return shard_map(shard_fn, mesh=mesh, in_specs=P(axis),
                         out_specs=P(axis))(x)
    return f


def reduce_scatter(stacked, mesh, axis="dp"):
    return _reduce_scatter_fn(mesh, axis)(stacked)


@functools.lru_cache(maxsize=None)
def _allgather_fn(mesh, axis):
    @jax.jit
    def f(x):
        return shard_map(
            lambda s: jax.lax.all_gather(s, axis, axis=0, tiled=True),
            mesh=mesh, in_specs=P(axis), out_specs=P(),
            check_rep=False)(x)
    return f


def allgather(shards, mesh, axis="dp"):
    return _allgather_fn(mesh, axis)(shards)


def broadcast(x, mesh):
    """Replicate a host/single-device array across the mesh."""
    from jax.sharding import NamedSharding
    return jax.device_put(x, NamedSharding(mesh, P()))
