"""Sequence/context parallelism: ring attention over the ICI mesh.

The reference has no sequence parallelism (SURVEY §5.7 — bucketing and the
fused RNN op were its only sequence-scaling tools).  The TPU-native stance:
shard the sequence dimension over a mesh axis and run *ring attention* —
each device keeps its Q shard resident and rotates K/V shards around the
ring with ``ppermute`` while accumulating blockwise online-softmax partials,
so attention over a sequence of length S costs O(S/n) memory per chip and
the K/V transfers ride the ICI ring concurrently with compute.

``ring_attention_shard`` is the per-shard function (use inside shard_map /
pjit with a bound axis name); ``sequence_parallel_attention`` is the
host-level wrapper that builds the shard_map over a mesh axis.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ..ops.attention import (_NEG_INF, _finalize_softmax,
                             _online_softmax_update)

__all__ = ["ring_attention_shard", "sequence_parallel_attention"]


def ring_attention_shard(q, k, v, axis_name, causal=False, sm_scale=None):
    """Ring attention on one sequence shard; call inside shard_map.

    q, k, v: (B, H, S_local, D) — this device's contiguous slice of the
    sequence (device i holds positions [i*S_local, (i+1)*S_local)).
    Returns the (B, H, S_local, D) attention output for the local queries
    over the FULL global sequence.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    from .collectives import axis_size
    n = axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    s_loc_k = k.shape[2]
    # storage-dtype q for the score dot (bf16 at full MXU rate); the
    # online-softmax state stays f32 via preferred_element_type
    # global positions, sequence ends aligned (same convention as
    # ops.attention when seq_q != seq_k)
    q_pos = me * s_loc + jnp.arange(s_loc) + (s_loc_k - s_loc) * n
    # receive from the right, send to the left: after step t this device
    # holds the K/V shard that originated at (me + t) % n
    perm = [(i, (i - 1) % n) for i in range(n)]

    def body(carry, t):
        o, m, l, kb, vb = carry
        src = (me + t) % n
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kb,
                       preferred_element_type=jnp.float32) * sm_scale
        if causal:
            k_pos = src * s_loc_k + jnp.arange(s_loc_k)
            mask = k_pos[None, :] <= q_pos[:, None]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        o, m, l = _online_softmax_update(o, m, l, s, vb)
        # rotate K/V one hop around the ring (overlaps with next compute
        # under XLA's async collective scheduling)
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (o, m, l, kb, vb), None

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    (o, m, l, _, _), _ = jax.lax.scan(
        jax.checkpoint(body), (o0, m0, l0, k, v), jnp.arange(n))
    return _finalize_softmax(o, m, l).astype(q.dtype)


def sequence_parallel_attention(q, k, v, mesh, axis="sp", causal=False,
                                sm_scale=None):
    """Host-level ring attention: (B, H, S, D) arrays sharded (or to be
    sharded) on the sequence dim over mesh axis *axis*."""
    from jax.sharding import NamedSharding
    spec = P(None, None, axis, None)
    sh = NamedSharding(mesh, spec)
    # inputs may be committed to a single device (e.g. outputs of an
    # earlier jitted op) — place them onto the mesh first; remember the
    # original placement so imperative callers get the result back where
    # the rest of their ops run (inside pjit this wrapper isn't used —
    # ring_attention_shard composes directly)
    orig_dev = None
    if not isinstance(q, jax.core.Tracer) and hasattr(q, "devices"):
        try:
            devs = list(q.devices())
        except (AttributeError, TypeError, RuntimeError, ValueError):
            # abstract/uncommitted values have no devices; anything
            # else must propagate rather than silently lose the
            # caller's placement
            devs = []
        if len(devs) == 1:
            orig_dev = devs[0]
    q, k, v = (jax.device_put(a, sh) for a in (q, k, v))

    def fn(qs, ks, vs):
        return ring_attention_shard(qs, ks, vs, axis, causal=causal,
                                    sm_scale=sm_scale)

    out = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec, check_rep=False)(q, k, v)
    if orig_dev is not None:
        out = jax.device_put(out, orig_dev)
    return out
