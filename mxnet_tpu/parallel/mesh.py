"""Device mesh management.

The reference scales via NCCL rings + a GPU-topology tree planner
(src/kvstore/comm_tree.h, gpu_topology.h — Kernighan-Lin over the PCIe/
NVLink link matrix).  On TPU none of that exists: the ICI torus is known to
XLA, so "topology planning" reduces to naming mesh axes and annotating
shardings — XLA inserts and schedules the collectives.  This module owns
the process-wide `jax.sharding.Mesh` the rest of the framework uses.

Axis convention (the full parallelism vocabulary, SURVEY.md §5.7/§5.8):
  dp — data parallel            tp — tensor (model) parallel
  pp — pipeline parallel        sp — sequence/context parallel
  ep — expert parallel
"""

from __future__ import annotations

import contextlib
import threading

import numpy as _np
import jax
from jax.sharding import Mesh, PartitionSpec, NamedSharding

__all__ = ["make_mesh", "current_mesh", "use_mesh", "data_parallel_mesh",
           "PartitionSpec", "NamedSharding", "named_sharding"]

_state = threading.local()


def make_mesh(axes=None, devices=None):
    """Create a Mesh.

    axes: dict axis_name -> size (product must cover the device count;
    a -1 size is inferred), e.g. {"dp": -1} or {"dp": 2, "tp": 4}.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axes is None:
        axes = {"dp": n}
    names = list(axes.keys())
    sizes = list(axes.values())
    n_known = 1
    for s in sizes:
        if s != -1:
            n_known *= s
    sizes = [s if s != -1 else n // n_known for s in sizes]
    total = 1
    for s in sizes:
        total *= s
    if total != n:
        raise ValueError("mesh axes %s do not cover %d devices" %
                         (dict(zip(names, sizes)), n))
    dev_array = _np.array(devices[:total]).reshape(sizes)
    return Mesh(dev_array, names)


def data_parallel_mesh(n=None):
    devs = jax.devices()
    if n is not None:
        devs = devs[:n]
    return make_mesh({"dp": len(devs)}, devs)


def current_mesh():
    return getattr(_state, "mesh", None)


@contextlib.contextmanager
def use_mesh(mesh):
    prev = getattr(_state, "mesh", None)
    _state.mesh = mesh
    try:
        yield mesh
    finally:
        _state.mesh = prev


def named_sharding(mesh, *spec):
    return NamedSharding(mesh, PartitionSpec(*spec))
