"""Pipeline parallelism over a mesh axis (GPipe-style loop skew).

The reference's only inter-device model splitting is manual `group2ctx`
placement (SURVEY §2.3); the TPU-native generalisation is a pipeline
axis: stage i's weights live on device i of the ``pp`` axis, microbatches
stream through with `ppermute` passing activations stage-to-stage, and
the whole schedule is one `lax.scan` inside `shard_map` — XLA overlaps
the per-tick compute with the neighbor transfer.

``pipeline_apply`` is differentiable (scan + ppermute have VJPs), so a
training step can `jax.grad` straight through the pipeline.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax.experimental.shard_map import shard_map

from .collectives import ppermute_ring

__all__ = ["pipeline_apply"]


def pipeline_apply(stage_fn, stage_params, x_micro, axis_name="pp",
                   mesh=None, x_spec=None):
    """Run S pipeline stages over microbatches.

    stage_fn(params_i, x) -> y : one stage's computation (same shape in
        and out across stages, the usual transformer-block case).
    stage_params : pytree whose leaves have leading dim S — leaf i is
        stage i's weights (sharded over *axis_name*).
    x_micro : (M, B, ...) microbatched input (replicated, or laid out
        per *x_spec* — e.g. P(None, "dp") composes the pipeline with a
        data-parallel batch axis; outputs keep the same layout).
    Returns (M, B, ...) outputs of the final stage.

    Schedule: T = M + S - 1 ticks of [receive from left neighbor ->
    compute my stage -> emit right] with the classic skew: stage s works
    on microbatch t - s at tick t; devices idle in the ramp-up/down
    bubble compute zeros (masked out of the result).
    """

    def shard_fn(params, xm):
        # params leaves arrive with leading dim 1 (this stage's slice)
        params = jax.tree.map(lambda a: a[0], params)
        s = jax.lax.axis_index(axis_name)
        from .collectives import axis_size
        n_stage = axis_size(axis_name)
        m = xm.shape[0]
        ticks = m + n_stage - 1
        out_shape = xm.shape[1:]

        def tick(carry, t):
            prev_out, outputs = carry
            # activation entering this stage this tick
            recv = ppermute_ring(prev_out, axis_name)
            mb_idx = jnp.clip(t, 0, m - 1)
            first = jnp.where(t < m, xm[mb_idx],
                              jnp.zeros(out_shape, xm.dtype))
            inp = jnp.where(s == 0, first, recv)
            # bubble ticks (stage s idle: t - s outside [0, m)) must not
            # evaluate stage_fn on garbage — a fn whose Jacobian is
            # non-finite at zeros (normalization layers) would leak NaN
            # into the scan transpose.  Double-where: feed a safe dummy
            # input on bubble ticks and zero the result.
            working = (t - s >= 0) & (t - s < m)
            safe_inp = jnp.where(working, inp,
                                 jnp.ones(out_shape, xm.dtype))
            out = jnp.where(working, stage_fn(params, safe_inp), 0.0)
            # last stage collects microbatch t - (S-1) at tick t
            coll_idx = t - (n_stage - 1)
            valid = (s == n_stage - 1) & (coll_idx >= 0)
            outputs = jax.lax.cond(
                valid,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, out, jnp.maximum(coll_idx, 0), 0),
                lambda o: o, outputs)
            return (out, outputs), None

        init_out = jnp.zeros(out_shape, xm.dtype)
        outputs0 = jnp.zeros((m,) + out_shape, xm.dtype)
        (_, outputs), _ = jax.lax.scan(tick, (init_out, outputs0),
                                       jnp.arange(ticks))
        # every device carries the buffer; only the last stage filled it —
        # broadcast it back so the result is replicated
        outputs = jax.lax.psum(
            jnp.where(s == n_stage - 1, outputs, 0.0), axis_name)
        return outputs

    if mesh is not None:
        param_specs = jax.tree.map(lambda _: P(axis_name), stage_params)
        xs = P() if x_spec is None else x_spec
        return shard_map(shard_fn, mesh=mesh,
                         in_specs=(param_specs, xs),
                         out_specs=xs, check_rep=False)(
            stage_params, x_micro)
    return shard_fn(stage_params, x_micro)
