"""Multi-host (multi-process) initialization and batch plumbing.

The reference scales across hosts with ps-lite processes launched by
`tools/launch.py` under `DMLC_*` env vars (SURVEY §2.3, §5.8).  The
TPU-native equivalent is jax.distributed: every process joins one
coordinator, `jax.devices()` becomes the GLOBAL device list (local
chips + every peer's), and a `Mesh` over it makes XLA route collectives
over ICI within a slice and DCN across slices — no NCCL/MPI port.

`init_multihost()` reads BOTH naming schemes, so the reference's
launcher bootstraps this path unchanged:

- DMLC_PS_ROOT_URI / DMLC_PS_ROOT_PORT -> coordinator address
- DMLC_NUM_WORKER                      -> process count
- DMLC_WORKER_ID / DMLC_WORKER_RANK    -> process id
- or the jax-native COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID

Typical flow (each process)::

    from mxnet_tpu.parallel import multihost
    multihost.init_multihost()                  # env-driven
    mesh = multihost.global_mesh({"dp": -1})
    trainer = ParallelTrainer(net, loss, mesh=mesh, ...)
    trainer.fit_batch(x_local, y_local)         # host-local shards

`ParallelTrainer._device_batch` detects a mesh that spans processes and
assembles host-local arrays into global ones automatically
(`host_local_to_global`), so each host feeds only its own rows —
exactly the per-worker batch contract of the reference's data-parallel
kvstore path.
"""

from __future__ import annotations

import os

import jax

__all__ = ["init_multihost", "global_mesh", "host_local_to_global",
           "global_to_host_local", "is_multihost_mesh",
           "process_index", "process_count"]


def init_multihost(coordinator=None, num_processes=None,
                   process_id=None, **kwargs):
    """Join (or start) the jax.distributed coordination service.

    Arguments fall back to DMLC_* then jax-native env vars (table in
    the module docstring).  No-op if already initialized or if the
    process count resolves to 1."""
    env = os.environ
    if coordinator is None:
        uri = env.get("DMLC_PS_ROOT_URI")
        port = env.get("DMLC_PS_ROOT_PORT")
        if uri and port:
            coordinator = "%s:%s" % (uri, port)
        else:
            coordinator = env.get("COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = int(env.get("DMLC_NUM_WORKER",
                                    env.get("NUM_PROCESSES", 0)) or 0)
    if process_id is None:
        pid = env.get("DMLC_WORKER_ID",
                      env.get("DMLC_WORKER_RANK",
                              env.get("DMLC_RANK",
                                      env.get("PROCESS_ID"))))
        process_id = int(pid) if pid is not None else None
    if num_processes in (0, 1):
        return False
    if _distributed_initialized():
        return True
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id, **kwargs)
    return True


def _distributed_initialized():
    """``jax.distributed.is_initialized`` only exists in newer jax;
    fall back to the runtime's global coordination-client state."""
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    try:
        from jax._src import distributed as _dist
        return getattr(_dist.global_state, "client", None) is not None
    except (ImportError, AttributeError):
        # private-module layout changed again (module gone OR
        # global_state renamed): treat as uninitialized —
        # initialize() itself raises loudly if called twice
        return False


def process_index():
    return jax.process_index()


def process_count():
    return jax.process_count()


def global_mesh(axes, devices=None):
    """Mesh over the GLOBAL device list (all processes).  ``axes`` maps
    name -> extent with at most one -1 (inferred)."""
    from .mesh import make_mesh
    return make_mesh(axes, devices if devices is not None
                     else jax.devices())


def is_multihost_mesh(mesh):
    """True when the mesh contains devices owned by other processes."""
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def host_local_to_global(x, mesh, pspec):
    """Assemble per-host shard(s) into one global jax.Array.

    Each process passes its own rows of the batch; the result behaves
    as the concatenated global array laid out per ``pspec`` (the
    multihost feeding contract of the kvstore data-parallel path)."""
    from jax.experimental import multihost_utils
    return multihost_utils.host_local_array_to_global_array(
        x, mesh, pspec)


def global_to_host_local(x, mesh, pspec):
    """Inverse of :func:`host_local_to_global`: each process receives
    its own rows of a global array (e.g. its slice of predictions)."""
    from jax.experimental import multihost_utils
    return multihost_utils.global_array_to_host_local_array(
        x, mesh, pspec)
