"""Expert parallelism: top-1 MoE dispatch over a mesh axis.

Absent from the reference (SURVEY §2.3 lists DP + manual model
parallelism only); the TPU-native pattern is an ``ep`` mesh axis holding
one expert per device, with `all_to_all` shuffling token capacity
buffers device->expert and back — the Switch-Transformer dispatch
expressed as XLA collectives over ICI.

``moe_apply`` is differentiable; overflow beyond per-expert capacity is
dropped (standard top-1 capacity semantics) and the combine weights
carry the router probability so the gate learns.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax.experimental.shard_map import shard_map

__all__ = ["moe_apply"]


def moe_apply(expert_fn, expert_params, x, gate_w, axis_name="ep",
              mesh=None, capacity_factor=1.0):
    """Top-1 routed mixture of experts.

    expert_fn(params_e, x) -> y : one expert's computation ((tokens, D)
        in and out).
    expert_params : pytree, leaves with leading dim E (expert e's
        weights live on device e of *axis_name*).
    x : (B, D) tokens, sharded over *axis_name* on dim 0.
    gate_w : (D, E) router weights (replicated).
    Returns (B, D) with each token processed by its chosen expert,
    scaled by the router probability (zeros for dropped tokens).
    """

    def shard_fn(params, xs, gw):
        from ..ops.nn import top1_route
        params = jax.tree.map(lambda a: a[0], params)
        from .collectives import axis_size
        e = axis_size(axis_name)
        nloc, d = xs.shape
        cap = max(1, int(capacity_factor * nloc / e))
        _, gate, expert_idx, slot, keep = top1_route(xs, gw, cap)
        # dispatch buffer: (E, cap, D) of this device's tokens, plus a
        # filled-slot mask that travels with it
        disp = jnp.zeros((e, cap, d), xs.dtype)
        disp = disp.at[expert_idx, jnp.clip(slot, 0, cap - 1)].add(
            xs * keep[:, None])
        filled = jnp.zeros((e, cap), xs.dtype)
        filled = filled.at[expert_idx, jnp.clip(slot, 0, cap - 1)].add(
            keep.astype(xs.dtype))
        # all_to_all: dim0 (expert) scatters, gathers peer dim ->
        # (E_peers, cap, D) buffers destined for MY expert
        recv = jax.lax.all_to_all(disp, axis_name, split_axis=0,
                                  concat_axis=0, tiled=True)
        rmask = jax.lax.all_to_all(filled[..., None], axis_name,
                                   split_axis=0, concat_axis=0,
                                   tiled=True)
        rmask = rmask.reshape(e * cap, 1)
        # double-where: padding slots must not evaluate expert_fn on
        # zeros (NaN Jacobians of normalization-style experts would
        # poison the gradient) and must come back as exact zeros
        flat = recv.reshape(e * cap, d)
        safe = jnp.where(rmask > 0, flat, jnp.ones_like(flat))
        out = jnp.where(rmask > 0, expert_fn(params, safe), 0.0)
        out = out.reshape(e, cap, d)
        back = jax.lax.all_to_all(out, axis_name, split_axis=0,
                                  concat_axis=0, tiled=True)
        # un-dispatch: token i reads (expert_idx[i], slot[i])
        y = back[expert_idx, jnp.clip(slot, 0, cap - 1)]
        return y * (gate * keep)[:, None]

    if mesh is not None:
        param_specs = jax.tree.map(lambda _: P(axis_name), expert_params)
        return shard_map(shard_fn, mesh=mesh,
                         in_specs=(param_specs, P(axis_name), P()),
                         out_specs=P(axis_name), check_rep=False)(
            expert_params, x, gate_w)
    return shard_fn(expert_params, x, gate_w)
