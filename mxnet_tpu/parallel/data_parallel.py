"""In-graph data-parallel training — the ``kvstore='tpu'`` execution path.

The reference's data parallelism is host-orchestrated: per-GPU executors,
then KVStore push/pull moves gradients through NCCL/ps-lite
(SURVEY.md §2.3).  The TPU-native equivalent inverts this: the WHOLE
training step — forward, backward, gradient all-reduce, fused optimizer
update — is one pjit-compiled SPMD program over a `jax.sharding.Mesh`.
Parameters/optimizer state are replicated (or dp-sharded, ZeRO-style, with
``shard_params=True``); the batch is sharded over the ``dp`` axis; XLA's
SPMD partitioner inserts the psum over ICI where the gradients meet the
replicated parameters.  Buffer donation makes updates in-place in HBM.

This is what `bench.py` and `__graft_entry__.dryrun_multichip` run, and what
Gluon's Trainer uses when constructed with ``kvstore='tpu'``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import make_mesh
from .. import autograd
from ..ndarray import NDArray

__all__ = ["ParallelTrainer"]


_OPT_OPS = {
    # optimizer name -> (update op name, state factory)
    "sgd": ("sgd_update", lambda w: ()),
    "sgd_mom": ("sgd_mom_update", lambda w: (jnp.zeros_like(w),)),
    "adam": ("adam_update", lambda w: (jnp.zeros_like(w),
                                       jnp.zeros_like(w))),
}


class ParallelTrainer:
    """Compile a Gluon HybridBlock + loss + optimizer into one sharded
    train step.

    Parameters
    ----------
    net : HybridBlock (will be traced symbolically, like hybridize)
    loss : gluon loss HybridBlock
    optimizer : 'sgd' | 'adam' (+ hyperparams via optimizer_params);
        momentum>0 selects the momentum kernel
    mesh : jax Mesh (default: all devices on one 'dp' axis)
    shard_params : if True, parameters and optimizer state are sharded
        over dp on their leading axis when divisible (ZeRO-1-style);
        else replicated
    """

    def __init__(self, net, loss, optimizer="sgd", optimizer_params=None,
                 mesh=None, shard_params=False, grad_clip=None):
        self.net = net
        self.loss = loss
        self.mesh = mesh or make_mesh()
        self.opt_name = optimizer
        self.opt_params = dict(optimizer_params or {})
        self.shard_params = shard_params
        self.grad_clip = grad_clip
        self._step_fn = None
        self._params = None          # name -> jax array (device, sharded)
        self._opt_state = None
        self._aux = None
        self._graph = None
        self._num_update = 0

    # -- tracing -----------------------------------------------------------
    def _trace(self, x, y):
        from .. import symbol as sym_mod
        from ..executor import _build_eval
        data = sym_mod.var("data0")
        label = sym_mod.var("label0")
        out = self.net(data)
        loss_sym = self.loss(out, label)
        self._graph = loss_sym
        self._eval = _build_eval(loss_sym, True)
        args = loss_sym.list_arguments()
        self.param_names = [a for a in args if a not in ("data0", "label0")]
        self.aux_names = loss_sym.list_auxiliary_states()

    def _gather_state(self):
        params = {p.name: p for p in self.net.collect_params().values()}
        repl = NamedSharding(self.mesh, P())
        self._params = {}
        for n in self.param_names:
            arr = params[n].data()._data
            self._params[n] = jax.device_put(arr, self._shard_for(arr))
        self._aux = {n: jax.device_put(params[n].data()._data, repl)
                     for n in self.aux_names}
        opt_key = self.opt_name
        if opt_key == "sgd" and self.opt_params.get("momentum", 0):
            opt_key = "sgd_mom"
        self._opt_op, state_fn = _OPT_OPS[opt_key]
        self._opt_state = {n: tuple(
            jax.device_put(s, self._shard_for(s))
            for s in state_fn(self._params[n]))
            for n in self.param_names}

    def _shard_for(self, arr):
        ndp = self.mesh.shape.get("dp", 1)
        if self.shard_params and arr.ndim >= 1 and \
                arr.shape[0] % ndp == 0 and arr.shape[0] >= ndp:
            return NamedSharding(self.mesh, P("dp"))
        return NamedSharding(self.mesh, P())

    # -- compiled step -----------------------------------------------------
    def _build_step(self):
        from ..ops.registry import get_op
        eval_fn = self._eval
        opt_op = get_op(self._opt_op)
        opt_hp = {k: v for k, v in self.opt_params.items()
                  if k in opt_op.param_names}
        grad_clip = self.grad_clip

        def train_step(params, opt_state, aux, x, y, key, lr):
            def loss_of(p):
                amap = dict(p)
                amap["data0"] = x
                amap["label0"] = y
                outs, auxu = eval_fn(amap, aux, key)
                return jnp.mean(outs[0]), auxu

            (loss_val, auxu), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            if grad_clip is not None:
                gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g))
                                     for g in grads.values()))
                scale = jnp.minimum(1.0, grad_clip / (gnorm + 1e-8))
                grads = {k: g * scale for k, g in grads.items()}
            new_params = {}
            new_state = {}
            hp = dict(opt_hp)
            hp["lr"] = lr
            for n, w in params.items():
                out = opt_op.fn(w, grads[n], *opt_state[n], **hp)
                if not isinstance(out, tuple):
                    out = (out,)
                new_params[n] = out[0]
                new_state[n] = tuple(out[1:])
            new_aux = dict(aux)
            new_aux.update(auxu)
            return new_params, new_state, new_aux, loss_val

        repl = NamedSharding(self.mesh, P())
        batch_sh = NamedSharding(self.mesh, P("dp"))
        param_sh = {n: self._shard_for(self._params[n])
                    for n in self._params}
        state_sh = {n: tuple(self._shard_for(s) for s in self._opt_state[n])
                    for n in self._opt_state}
        aux_sh = {n: repl for n in self._aux}
        self._step_fn = jax.jit(
            train_step,
            in_shardings=(param_sh, state_sh, aux_sh,
                          batch_sh, batch_sh, repl, None),
            # pin outputs to the input layout so the params/state returned
            # by step N are valid inputs for step N+1 (otherwise XLA's
            # sharding propagation may choose a different layout)
            out_shardings=(param_sh, state_sh, aux_sh, repl),
            donate_argnums=(0, 1, 2))
        self._key = jax.random.PRNGKey(0)

    def fit_batch(self, x, y):
        """Run one training step; returns the (replicated) mean loss."""
        if isinstance(x, NDArray):
            x = x._data
        if isinstance(y, NDArray):
            y = y._data
        if self._step_fn is None:
            self.net._ensure_params(NDArray(x))
            self._trace(x, y)
            self._gather_state()
            self._build_step()
        batch_sh = NamedSharding(self.mesh, P("dp"))
        x = jax.device_put(x, batch_sh)
        y = jax.device_put(y, batch_sh)
        self._key, sub = jax.random.split(self._key)
        lr = jnp.asarray(self.opt_params.get("learning_rate", 0.01),
                         jnp.float32)
        self._params, self._opt_state, self._aux, loss = self._step_fn(
            self._params, self._opt_state, self._aux, x, y, sub, lr)
        self._num_update += 1
        return loss

    # -- sync back to gluon parameters --------------------------------------
    def sync_params(self):
        """Write the trained values back into the Block's Parameters
        (gathered to a single device so eager ops can consume them)."""
        import numpy as _np
        params = {p.name: p for p in self.net.collect_params().values()}
        for n, arr in self._params.items():
            params[n].data()._data = jnp.asarray(_np.asarray(arr))
        for n, arr in self._aux.items():
            params[n].data()._data = jnp.asarray(_np.asarray(arr))

    @property
    def params(self):
        return self._params
