"""In-graph data-parallel training — the ``kvstore='tpu'`` execution path.

The reference's data parallelism is host-orchestrated: per-GPU executors,
then KVStore push/pull moves gradients through NCCL/ps-lite
(SURVEY.md §2.3).  The TPU-native equivalent inverts this: the WHOLE
training step — forward, backward, gradient all-reduce, fused optimizer
update — is one pjit-compiled SPMD program over a `jax.sharding.Mesh`.
Parameters/optimizer state are replicated (or dp-sharded, ZeRO-style, with
``shard_params=True``); the batch is sharded over the ``dp`` axis; XLA's
SPMD partitioner inserts the psum over ICI where the gradients meet the
replicated parameters.  Buffer donation makes updates in-place in HBM.

Supports every fused update op in ops/optimizer_ops.py, bf16
multi-precision training (bf16 compute weights + f32 master copies via
the mp_sgd ops' scheme — reference optimizer_op.cc mp_sgd), and
LARS/LBSGD layer-wise adaptive rates (reference optimizer.py:678) — the
ResNet-50 north-star configuration.

This is what `bench.py` and `__graft_entry__.dryrun_multichip` run, and
what Gluon's Trainer uses when constructed with ``kvstore='tpu'``.
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import make_mesh
from ..ndarray import NDArray
from ..observability import metrics as _obs_metrics

__all__ = ["ParallelTrainer"]

# module-level instrument ref (hot path: consulted per fit_batch) —
# same registry instrument the ndarray/executor placement paths bump
_DEVICE_PUT_ELIDED = _obs_metrics.counter(
    "device_put_elided_total",
    "host->device transfers skipped because the array was already "
    "committed to its target device/sharding (device-resident input)")


# optimizer name -> (update op, number of zero-init states).
# State layout convention of the fused ops: fn(weight, grad, *states,
# **hyper) -> (new_weight, *new_states).
_OPT_OPS = {
    "sgd": ("sgd_update", 0),
    "sgd_mom": ("sgd_mom_update", 1),
    "nag": ("nag_mom_update", 1),
    "adam": ("adam_update", 2),
    "rmsprop": ("rmsprop_update", 1),
    "rmspropalex": ("rmspropalex_update", 3),
    "ftrl": ("ftrl_update", 2),
    "ftml": ("ftml_update", 3),
    "signum": ("signum_update", 1),
    "signsgd": ("signsgd_update", 0),
    "adadelta": ("adadelta_update", 2),
    "adamax": ("adamax_update", 2),
    "nadam": ("nadam_update", 2),
}

# LARS-family: layer-wise trust ratio scaling wrapped around momentum sgd
_LARS_NAMES = ("lars", "lbsgd")


class ParallelTrainer:
    """Compile a Gluon HybridBlock + loss + optimizer into one sharded
    train step.

    Parameters
    ----------
    net : HybridBlock (traced symbolically, like hybridize)
    loss : gluon loss HybridBlock
    optimizer : any name in ops/optimizer_ops.py ('sgd', 'adam',
        'rmsprop', ...) or 'lars'/'lbsgd'; momentum>0 upgrades sgd to
        the momentum kernel
    mesh : jax Mesh (default: all devices on one 'dp' axis)
    shard_params : ZeRO-1-style dp-sharding of params + optimizer state
    multi_precision : train with bf16 compute weights + f32 master
        copies (bf16 batches, f32 loss/update math)
    grad_clip : optional global-norm clip
    """

    def __init__(self, net, loss, optimizer="sgd", optimizer_params=None,
                 mesh=None, shard_params=False, grad_clip=None,
                 multi_precision=False, remat=None, coalesce_small=None,
                 param_specs=None):
        self.net = net
        self.loss = loss
        self.mesh = mesh or make_mesh()
        self.opt_name = optimizer
        self.opt_params = dict(optimizer_params or {})
        self.shard_params = shard_params
        from .multihost import is_multihost_mesh
        self._multihost = is_multihost_mesh(self.mesh)
        if shard_params and self._multihost:
            raise NotImplementedError(
                "shard_params (ZeRO) over a multi-host mesh needs "
                "host-local shard feeding; use replicated params")
        if self._multihost:
            # the host-local batch contract assumes processes partition
            # the mesh ALONG dp: every device's owning process must be
            # a function of its dp coordinate alone (frozen-state
            # scaling and host_local_to_global both build on it)
            import numpy as _onp
            names = list(self.mesh.axis_names)
            if "dp" not in names:
                raise NotImplementedError(
                    "a multi-host mesh needs a 'dp' axis spanning the "
                    "processes (got axes %s)" % names)
            dp_axis = names.index("dp")
            owner_of_dp = {}
            for idx, dev in _onp.ndenumerate(self.mesh.devices):
                prev = owner_of_dp.setdefault(idx[dp_axis],
                                              dev.process_index)
                if prev != dev.process_index:
                    raise NotImplementedError(
                        "multi-host meshes must span processes along "
                        "the dp axis only (dp index %d maps to "
                        "processes %d and %d)"
                        % (idx[dp_axis], prev, dev.process_index))
        self.grad_clip = grad_clip
        self.multi_precision = multi_precision
        # coalesce_small: apply the optimizer (and the LARS trust-ratio
        # norms) to all SMALL parameters — BN scales/biases and the like
        # — as one fused flat-buffer computation instead of hundreds of
        # tiny per-tensor kernels.  A ResNet-50 LARS step otherwise pays
        # ~2 norm reductions + an update kernel for each of ~110 tiny
        # tensors, pure kernel-launch overhead on TPU.  Default: on for
        # the LARS family with the (mp_)sgd kernels (the north-star
        # config); only supported for those kernels and for replicated
        # (non-ZeRO) parameter layouts.
        self.coalesce_small = coalesce_small
        # param_specs: tensor parallelism at the trainer level — a dict
        # mapping a parameter-name regex to the PartitionSpec its
        # weight (and optimizer state) lives at, e.g. a megatron MLP:
        #   {r"fc1.*weight": P("tp", None),   # column-parallel
        #    r"fc2.*weight": P(None, "tp")}   # row-parallel
        # First match wins; unmatched params follow the replicated /
        # ZeRO-dp default.  XLA's SPMD partitioner closes the tp
        # collectives inside the compiled step.
        self.param_specs = dict(param_specs or {})
        # rematerialization policy for the fwd activations kept for
        # backward: None (XLA decides), 'full' (recompute everything —
        # min HBM), 'dots' (save matmul/conv outputs only, recompute the
        # cheap elementwise chains — the usual sweet spot), or any
        # jax.checkpoint policy callable
        self.remat = remat
        self._step_fn = None
        self._eval_fn = None
        self._params = None          # name -> jax array (device, sharded)
        self._opt_state = None
        self._aux = None
        self._graph = None
        self._num_update = 0

    # -- tracing -----------------------------------------------------------
    def _trace(self, x, y):
        from .. import symbol as sym_mod
        from ..executor import _build_eval
        data = sym_mod.var("data0")
        label = sym_mod.var("label0")
        out = self.net(data)
        loss_sym = self.loss(out, label)
        self._graph = loss_sym
        self._eval = _build_eval(loss_sym, True)
        self._eval_infer = _build_eval(loss_sym, False)
        out_syms = out if isinstance(out, sym_mod.Symbol) else out[0]
        self._fwd_eval = _build_eval(out_syms, False)
        args = loss_sym.list_arguments()
        self.param_names = [a for a in args if a not in ("data0", "label0")]
        self.aux_names = loss_sym.list_auxiliary_states()

    def _resolve_opt(self):
        from ..ops.registry import get_op
        name = self.opt_name
        self._lars = name in _LARS_NAMES
        if self._lars:
            name = "sgd"
        if name == "sgd" and self.opt_params.get("momentum", 0):
            name = "sgd_mom"
        if name not in _OPT_OPS:
            raise ValueError(
                "optimizer %r not supported by ParallelTrainer; one of %s"
                % (self.opt_name, sorted(_OPT_OPS) + list(_LARS_NAMES)))
        base_op, n_states = _OPT_OPS[name]
        self._opt_base = name
        if self.multi_precision:
            if name not in ("sgd", "sgd_mom"):
                raise ValueError(
                    "multi_precision needs the mp_sgd update kernels; "
                    "use optimizer='sgd'/'lars'/'lbsgd' (got %r)"
                    % self.opt_name)
            base_op = "mp_" + base_op
        self._opt_op = get_op(base_op)
        self._opt_n_states = n_states

    def _gather_state(self, data_shape=None, label_shape=None):
        params = {p.name: p for p in self.net.collect_params().values()}
        repl = NamedSharding(self.mesh, P())
        self._resolve_opt()
        # graph arguments with no backing Parameter (e.g. the fused RNN
        # op's auto-created begin-state vars) are zero-filled constant
        # inputs, exactly like simple_bind's unbound-arg semantics —
        # they get no optimizer state and pass through the step frozen
        self._frozen = frozenset(
            n for n in self.param_names if n not in params)
        frozen_arrays = {}
        if self._frozen:
            frozen_arrays = self._infer_frozen(data_shape, label_shape)
            self._frozen_built_for = (tuple(data_shape or ()),
                                      tuple(label_shape or ()))
        self._params = {}
        self._opt_state = {}
        for n in self.param_names:
            if n in self._frozen:
                self._params[n] = self._put(frozen_arrays[n], P())
                self._opt_state[n] = ()
                continue
            arr, states = self._state_for_array(params[n].data()._data)
            self._params[n] = self._put(arr, self._spec_for(arr, n))
            self._opt_state[n] = tuple(
                self._put(s, self._spec_for(s, n)) for s in states)
        self._aux = {n: self._put(params[n].data()._data, P())
                     for n in self.aux_names}

    def _state_for_array(self, arr):
        """(stored array, fresh optimizer states) for one parameter,
        honoring multi_precision (bf16 compute + f32 master copy)."""
        if self.multi_precision:
            master = arr.astype(jnp.float32)
            arr = arr.astype(jnp.bfloat16)
            # f32 states + trailing f32 master copy (mp op signature:
            # ..., mom, weight32)
            states = [jnp.zeros_like(master)
                      for _ in range(self._opt_n_states)]
            states.append(master)
        else:
            # states match the stored weight dtype so fused updates
            # neither promote nor retrace
            states = [jnp.zeros_like(arr)
                      for _ in range(self._opt_n_states)]
        return arr, states

    def _infer_frozen(self, data_shape, label_shape):
        """Zero arrays for the frozen (non-Parameter) graph args at the
        shapes inference yields for this batch geometry."""
        params = {p.name: p for p in self.net.collect_params().values()}
        cdtype = jnp.bfloat16 if self.multi_precision else None

        def _global(shape):
            # callers pass HOST-LOCAL batch shapes; the compiled step
            # sees the global batch (rows concatenated across hosts)
            if shape is None or not self._multihost:
                return shape
            shape = tuple(shape)
            import jax as _jax
            return (shape[0] * _jax.process_count(),) + shape[1:]

        shapes = {}
        data_shape = _global(data_shape)
        label_shape = _global(label_shape)
        if data_shape is not None:
            shapes["data0"] = tuple(data_shape)
        if label_shape is not None:
            shapes["label0"] = tuple(label_shape)
        # every materialized Parameter shape is a known — only the
        # frozen args are left for inference to solve
        for pname, p in params.items():
            shp = getattr(p, "shape", None)
            if pname in self.param_names and shp and \
                    all(int(s) > 0 for s in shp):
                shapes[pname] = tuple(int(s) for s in shp)
        arg_shapes, _, _ = self._graph.infer_shape(**shapes)
        inferred = dict(zip(self._graph.list_arguments(), arg_shapes))
        return {n: jnp.zeros(inferred[n], cdtype or jnp.float32)
                for n in self._frozen}

    def _refresh_frozen(self, x_shape, y_shape=None):
        """Frozen begin-states are shaped by the batch geometry; a new
        batch size means new zeros (the step retraces anyway).  With no
        label (predict), the label shape is derived from the stored one
        at the new batch size."""
        if not self._frozen:
            return
        if y_shape is None:
            tail = self._frozen_built_for[1][1:]
            y_shape = (tuple(x_shape)[0],) + tuple(tail)
        key = (tuple(x_shape), tuple(y_shape))
        if key == self._frozen_built_for:
            return
        for n, z in self._infer_frozen(x_shape, y_shape).items():
            self._params[n] = self._put(z, P())
        self._frozen_built_for = key

    def _put(self, arr, spec):
        """Place an array at (mesh, spec).  On a mesh spanning several
        processes, device_put cannot move bytes across hosts — instead
        every process contributes its local copy/shard
        (multihost_utils), which is the SPMD contract: replicated
        values must already be identical on every host (same init
        seed), sharded values must be the host-local rows."""
        if self._mesh_is_multihost():
            from .multihost import host_local_to_global
            return host_local_to_global(jnp.asarray(arr), self.mesh,
                                        spec)
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def _spec_for(self, arr, name=None):
        if name is not None:
            for pat, spec in self.param_specs.items():
                if re.search(pat, name):
                    return spec
        ndp = self.mesh.shape.get("dp", 1)
        if self.shard_params and arr.ndim >= 1 and \
                arr.shape[0] % ndp == 0 and arr.shape[0] >= ndp:
            return P("dp")
        return P()

    def _shard_for(self, arr, name=None):
        return NamedSharding(self.mesh, self._spec_for(arr, name))

    # -- compiled step -----------------------------------------------------
    def _build_step(self):
        eval_fn = self._eval
        opt_op = self._opt_op
        opt_hp = {k: v for k, v in self.opt_params.items()
                  if k in opt_op.param_names and k not in ("lr", "t")}
        grad_clip = self.grad_clip
        lars = self._lars
        lars_eta = float(self.opt_params.get("eta", 0.001))
        lars_eps = float(self.opt_params.get("epsilon", 1e-9))
        wd = float(self.opt_params.get("wd", 0.0))
        mp = self.multi_precision

        # -- coalesced small-parameter apply (see __init__ docstring) --
        import numpy as onp
        coalesce = self.coalesce_small
        if coalesce is None:
            coalesce = lars
        supported = (not self.shard_params
                     and self._opt_base in ("sgd", "sgd_mom"))
        if self.coalesce_small and not supported:
            raise ValueError(
                "coalesce_small=True requires an (mp_)sgd[_mom] optimizer "
                "and shard_params=False (got optimizer base %r, "
                "shard_params=%r); drop the flag to use the per-tensor "
                "apply path" % (self._opt_base, self.shard_params))
        coalesce = coalesce and supported
        small = []
        if coalesce:
            _SMALL_MAX = 8192
            small = [n for n in self.param_names
                     if n not in self._frozen
                     and self._params[n].size <= _SMALL_MAX
                     and not any(re.search(p, n)
                                 for p in self.param_specs)]
            coalesce = len(small) >= 2
        if coalesce:
            small_set = frozenset(small)
            c_shapes = [self._params[n].shape for n in small]
            c_sizes = onp.array([max(1, int(onp.prod(s)))
                                 for s in c_shapes])
            # pad each tensor to the 128-lane boundary so the chunked
            # row sums below never mix two parameters in one chunk
            c_psz = ((c_sizes + 127) // 128) * 128
            c_offs = onp.concatenate(([0], onp.cumsum(c_psz)))[:-1]
            c_total = int(c_psz.sum())
            # chunk -> parameter one-hot selector: per-parameter squared
            # sums become ONE (n_small, n_chunks) f32 matmul over the
            # chunk partials instead of n_small tiny reductions
            c_seg = onp.repeat(onp.arange(len(small)), c_psz // 128)
            c_sel = onp.zeros((len(small), c_total // 128), onp.float32)
            c_sel[c_seg, onp.arange(c_total // 128)] = 1.0
            c_sel = jnp.asarray(c_sel)
            c_mom = float(self.opt_params.get("momentum", 0.0))
            c_rescale = float(self.opt_params.get("rescale_grad", 1.0))
            c_clip = float(self.opt_params.get("clip_gradient", -1.0))
            c_has_mom = self._opt_base == "sgd_mom"

            def _apply_small(params, grads, opt_state, lr):
                def flat(pieces):
                    return jnp.concatenate([
                        jnp.pad(p.reshape(-1).astype(jnp.float32),
                                (0, int(ps - sz)))
                        for p, sz, ps in zip(pieces, c_sizes, c_psz)])
                w32f = flat([opt_state[n][-1] if mp else params[n]
                             for n in small])
                gf = flat([grads[n] for n in small])
                if lars:
                    # the per-tensor path computes these norms with
                    # jnp.sum (f32 regardless of matmul precision), so
                    # this contraction is pinned to HIGHEST outright —
                    # not via matmul_precision(), whose env override
                    # would silently de-sync the two paths
                    prec = jax.lax.Precision.HIGHEST
                    wsq = jnp.matmul(
                        c_sel, jnp.sum(w32f.reshape(-1, 128) ** 2, axis=1),
                        precision=prec)
                    gsq = jnp.matmul(
                        c_sel, jnp.sum(gf.reshape(-1, 128) ** 2, axis=1),
                        precision=prec)
                    wnorm = jnp.sqrt(wsq)
                    gnorm = jnp.sqrt(gsq)
                    trust = jnp.where(
                        (wnorm > 0) & (gnorm > 0),
                        lars_eta * wnorm / (gnorm + wd * wnorm +
                                            lars_eps),
                        1.0)
                    lr_elem = jnp.repeat(lr * trust, c_psz,
                                         total_repeat_length=c_total)
                else:
                    lr_elem = lr
                # exact (mp_)sgd[_mom] update math on the flat buffer
                # (ops/optimizer_ops.py _rescale_clip order: rescale ->
                # clip -> + wd*w32)
                g = gf * c_rescale
                if c_clip >= 0:
                    g = jnp.clip(g, -c_clip, c_clip)
                g = g + wd * w32f
                if c_has_mom:
                    momf = flat([opt_state[n][0] for n in small])
                    momf = c_mom * momf - lr_elem * g
                    w32f = w32f + momf
                else:
                    w32f = w32f - lr_elem * g
                out_p, out_s = {}, {}
                for i, n in enumerate(small):
                    o, sz = int(c_offs[i]), int(c_sizes[i])
                    w32n = w32f[o:o + sz].reshape(c_shapes[i])
                    out_p[n] = w32n.astype(params[n].dtype)
                    st = []
                    if c_has_mom:
                        st.append(momf[o:o + sz].reshape(c_shapes[i]))
                    if mp:
                        st.append(w32n)
                    out_s[n] = tuple(st)
                return out_p, out_s
        else:
            small_set = frozenset()
            _apply_small = None

        frozen = self._frozen
        remat = self.remat
        if remat is not None:
            policy = None
            if remat == "dots":
                policy = jax.checkpoint_policies \
                    .dots_with_no_batch_dims_saveable
            elif callable(remat):
                policy = remat
            elif remat != "full":
                raise ValueError("remat must be None, 'full', 'dots' or "
                                 "a jax.checkpoint policy")

        def train_step(params, opt_state, aux, x, y, key, lr, t):
            # trace-time only — the compile counter for the sharded step
            # (cached executions bump nothing; see profiler.py counters)
            from .. import profiler as _prof
            _prof.bump_counter(  # graftlint: disable=JG003
                "parallel_step_compiles")  # trace-time-only on purpose

            def loss_of(p):
                amap = dict(p)
                amap["data0"] = x
                amap["label0"] = y
                outs, auxu = eval_fn(amap, aux, key)
                return jnp.mean(outs[0].astype(jnp.float32)), auxu

            if remat is not None:
                loss_of = jax.checkpoint(loss_of, policy=policy)
            (loss_val, auxu), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            if grad_clip is not None:
                gnorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for n, g in grads.items() if n not in frozen))
                scale = jnp.minimum(1.0, grad_clip / (gnorm + 1e-8))
                grads = {k: (g.astype(jnp.float32) * scale).astype(g.dtype)
                         for k, g in grads.items()}
            new_params = {}
            new_state = {}
            hp = dict(opt_hp)
            if "t" in opt_op.param_names:
                hp["t"] = t
            for n, w in params.items():
                if n in frozen:
                    # zero-filled non-Parameter graph inputs (RNN
                    # begin-states): never updated
                    new_params[n] = w
                    new_state[n] = ()
                    continue
                if n in small_set:
                    continue
                g = grads[n]
                lr_n = lr
                if lars:
                    # layer-wise trust ratio (reference LBSGD:678):
                    # lr_layer = lr * eta * ||w|| / (||g|| + wd*||w||)
                    w32 = opt_state[n][-1] if mp else \
                        w.astype(jnp.float32)
                    wnorm = jnp.sqrt(jnp.sum(jnp.square(w32)))
                    gnorm = jnp.sqrt(jnp.sum(
                        jnp.square(g.astype(jnp.float32))))
                    trust = jnp.where(
                        (wnorm > 0) & (gnorm > 0),
                        lars_eta * wnorm / (gnorm + wd * wnorm +
                                            lars_eps),
                        1.0)
                    lr_n = lr * trust
                out = opt_op.fn(w, g, *opt_state[n], lr=lr_n, **hp)
                if not isinstance(out, tuple):
                    out = (out,)
                new_params[n] = out[0]
                new_state[n] = tuple(out[1:])
            if _apply_small is not None:
                sp, ss = _apply_small(params, grads, opt_state, lr)
                new_params.update(sp)
                new_state.update(ss)
            new_aux = dict(aux)
            new_aux.update(auxu)
            return new_params, new_state, new_aux, loss_val

        repl = NamedSharding(self.mesh, P())
        batch_sh = NamedSharding(self.mesh, P("dp"))
        # frozen args always live replicated, whatever param_specs says
        param_sh = {n: self._shard_for(self._params[n],
                                       None if n in self._frozen else n)
                    for n in self._params}
        state_sh = {n: tuple(self._shard_for(
                        s, None if n in self._frozen else n)
                             for s in self._opt_state[n])
                    for n in self._opt_state}
        aux_sh = {n: repl for n in self._aux}
        self._step_fn = jax.jit(
            train_step,
            in_shardings=(param_sh, state_sh, aux_sh,
                          batch_sh, batch_sh, repl, None, None),
            # pin outputs to the input layout so the params/state returned
            # by step N are valid inputs for step N+1 (otherwise XLA's
            # sharding propagation may choose a different layout)
            out_shardings=(param_sh, state_sh, aux_sh, repl),
            donate_argnums=(0, 1, 2))

        eval_infer = self._eval_infer
        fwd_eval = self._fwd_eval

        def eval_step(params, aux, x, y, key):
            amap = dict(params)
            amap["data0"] = x
            amap["label0"] = y
            outs, _ = eval_infer(amap, aux, key)
            return jnp.mean(outs[0].astype(jnp.float32))

        def predict_step(params, aux, x, key):
            amap = dict(params)
            amap["data0"] = x
            outs, _ = fwd_eval(amap, aux, key)
            return outs[0]

        self._eval_fn = jax.jit(
            eval_step, in_shardings=(param_sh, aux_sh, batch_sh,
                                     batch_sh, repl))
        self._predict_fn = jax.jit(
            predict_step, in_shardings=(param_sh, aux_sh, batch_sh, repl),
            out_shardings=batch_sh)
        self._key = jax.random.PRNGKey(0)

    def _ensure_built(self, x, y):
        if self._step_fn is None:
            self.net._ensure_params(NDArray(x))
            self._trace(x, y)
            self._gather_state(data_shape=x.shape, label_shape=y.shape)
            self._build_step()

    def _device_batch(self, x):
        if isinstance(x, NDArray):
            x = x._data
        if self.multi_precision and jnp.issubdtype(x.dtype,
                                                   jnp.floating):
            x = x.astype(jnp.bfloat16)
        sh = NamedSharding(self.mesh, P("dp"))
        # already resident with the right layout (a DevicePrefetcher
        # ring batch, or the caller reusing an array a previous step
        # produced) — skip the transfer (counted, see
        # docs/perf_input_pipeline.md)
        if isinstance(x, jax.Array) and getattr(x, "sharding", None) == sh:
            _DEVICE_PUT_ELIDED.inc()
            return x
        # on a multihost mesh each process feeds only ITS rows and
        # _put assembles the global batch (multihost feeding contract)
        return self._put(x, P("dp"))

    def _mesh_is_multihost(self):
        return self._multihost

    def _label_batch(self, y):
        if isinstance(y, NDArray):
            y = y._data
        sh = NamedSharding(self.mesh, P("dp"))
        if isinstance(y, jax.Array) and getattr(y, "sharding", None) == sh:
            _DEVICE_PUT_ELIDED.inc()
            return y
        return self._put(y, P("dp"))

    def fit(self, train_data, num_epoch=1, checkpoint_prefix=None,
            batch_end_callback=None, logger=None, device_prefetch=None):
        """Epoch/batch loop over a ``DataIter`` — the trainer-level
        peer of ``Module.fit``, with the SAME batch-boundary
        resilience contract: a preemption request (SIGTERM flag,
        ``chaos.preempt_at_batch``) finishes the in-flight batch,
        writes a full-state checkpoint (params + optimizer state +
        aux + update counter, when *checkpoint_prefix* is given) and
        returns cleanly; every batch ticks the supervisor heartbeat.
        Returns the last batch's loss per epoch.

        ``device_prefetch=K`` (or ``MXNET_DEVICE_PREFETCH``) wraps
        *train_data* in a ``DevicePrefetcher`` bound to this trainer's
        MESH: batches arrive as ``NamedSharding(mesh, P('dp'))``
        arrays, so ``fit_batch``'s ``_device_batch`` skips its
        transfer entirely (docs/perf_input_pipeline.md)."""
        from ..io.device_prefetch import maybe_wrap
        # on a multi-host mesh device_put cannot place a global batch
        # (host_local_to_global owns that path in _device_batch) — the
        # wrap degrades to host-side decode overlap so batches reach
        # _device_batch unplaced and its multihost path runs once, not
        # after a wasted single-device transfer
        train_data, created_prefetcher = maybe_wrap(
            train_data, device_prefetch, mesh=self.mesh,
            decode_only=self._multihost)
        try:
            return self._fit_loop(train_data, num_epoch,
                                  checkpoint_prefix, batch_end_callback,
                                  logger)
        finally:
            if created_prefetcher:
                train_data.close()

    def _fit_loop(self, train_data, num_epoch, checkpoint_prefix,
                  batch_end_callback, logger):
        import logging as _logging
        from .. import resilience
        from ..resilience import supervisor as _sup
        log = logger or _logging.getLogger(__name__)
        losses = []
        for epoch in range(num_epoch):
            loss = None
            for nbatch, batch in enumerate(train_data):
                loss = self.fit_batch(batch.data[0], batch.label[0])
                if batch_end_callback is not None:
                    batch_end_callback(epoch, nbatch, loss)
                _sup.heartbeat()
                if resilience.preemption_requested(tick=True):
                    from ..observability import events as _obs_events
                    _obs_events.emit(
                        "preempt", epoch=epoch, batch=nbatch,
                        trainer="ParallelTrainer",
                        checkpointing=checkpoint_prefix is not None)
                    log.warning(
                        "preemption requested: checkpointing after "
                        "epoch %d batch %d and exiting ParallelTrainer"
                        ".fit", epoch, nbatch)
                    if checkpoint_prefix is not None:
                        self.save_checkpoint(checkpoint_prefix, epoch)
                    resilience.clear_preemption()
                    return losses
            losses.append(loss)
            if checkpoint_prefix is not None:
                self.save_checkpoint(checkpoint_prefix, epoch)
            train_data.reset()
        return losses

    def fit_batch(self, x, y):
        """Run one training step; returns the (replicated) mean loss."""
        if isinstance(x, NDArray):
            x = x._data
        if isinstance(y, NDArray):
            y = y._data
        from ..resilience import chaos
        chaos.on_train_step(self._num_update)
        self._ensure_built(x, y)
        self._refresh_frozen(x.shape, y.shape)
        xd = self._device_batch(x)
        yd = self._label_batch(y)
        self._key, sub = jax.random.split(self._key)
        lr = jnp.asarray(self._current_lr(), jnp.float32)
        t = jnp.asarray(self._num_update + 1, jnp.int32)
        from .. import profiler as _prof
        _prof.bump_counter("parallel_step_dispatches")
        self._params, self._opt_state, self._aux, loss = self._step_fn(
            self._params, self._opt_state, self._aux, xd, yd, sub, lr, t)
        self._num_update += 1
        return loss

    def _current_lr(self):
        sched = self.opt_params.get("lr_scheduler")
        if sched is not None:
            return float(sched(self._num_update))
        return float(self.opt_params.get("learning_rate", 0.01))

    def evaluate_batch(self, x, y):
        """Mean loss over one batch, inference mode (no aux updates)."""
        if isinstance(x, NDArray):
            x = x._data
        if isinstance(y, NDArray):
            y = y._data
        self._ensure_built(x, y)
        self._refresh_frozen(x.shape, y.shape)
        xd = self._device_batch(x)
        yd = self._label_batch(y)
        return self._eval_fn(self._params, self._aux, xd, yd,
                             jax.random.PRNGKey(0))

    def predict_batch(self, x):
        """Network outputs for one batch, inference mode."""
        if isinstance(x, NDArray):
            x = x._data
        if self._step_fn is None:
            raise RuntimeError("run fit_batch or evaluate_batch first")
        self._refresh_frozen(x.shape)
        xd = self._device_batch(x)
        out = self._predict_fn(self._params, self._aux, xd,
                               jax.random.PRNGKey(0))
        if self._multihost:
            # hand each process back ITS rows (the dp-sharded global
            # output is not locally addressable)
            from .multihost import global_to_host_local
            out = global_to_host_local(out, self.mesh, P("dp"))
        return NDArray(out)

    # -- checkpoint / resume -------------------------------------------------
    def save_checkpoint(self, prefix, epoch=0):
        """Write the FULL training state — params, optimizer state, aux
        (BN stats), update counter — in the framework checkpoint
        container (reference shape: Module.save_checkpoint +
        Trainer.save_states, fused into one file pair here because the
        compiled step owns all three).  Returns the params path."""
        import numpy as _np
        from .. import ndarray as _nd
        blob = {}
        # iterate param_names (graph topological order), NOT the state
        # dicts: jitted steps return dicts with SORTED keys, and
        # alphabetical order is not stable across name-counter suffixes
        # (dense10 < dense9) — the load-side positional remap depends on
        # structural order
        for n in self.param_names:
            blob["arg:%s" % n] = _nd.NDArray(self._params[n])
            for i, s in enumerate(self._opt_state[n]):
                blob["opt%d:%s" % (i, n)] = _nd.NDArray(s)
        for n in self.aux_names:
            blob["aux:%s" % n] = _nd.NDArray(self._aux[n])
        blob["meta:num_update"] = _nd.array(
            _np.asarray([self._num_update], _np.int64))
        path = "%s-%04d.params" % (prefix, epoch)
        _nd.save(path, blob)
        return path

    def load_checkpoint(self, prefix, epoch=0):
        """Restore state written by :meth:`save_checkpoint`; the trainer
        must already be built (same model/optimizer config)."""
        from .. import ndarray as _nd
        if self._step_fn is None:
            raise RuntimeError("build the trainer first (run one "
                               "fit_batch) before loading a checkpoint")
        loaded = _nd.load("%s-%04d.params" % (prefix, epoch))
        params, opt, aux = {}, {}, {}
        num_update = self._num_update
        for k, v in loaded.items():
            kind, name = k.split(":", 1)
            if kind == "arg":
                params[name] = v._data
            elif kind.startswith("opt"):
                opt.setdefault(name, {})[int(kind[3:])] = v._data
            elif kind == "aux":
                aux[name] = v._data
            elif k == "meta:num_update":
                num_update = int(v.asnumpy()[0])
        if set(params) != set(self._params):
            # same architecture under different auto-generated name
            # counters (e.g. several nets built in one process): map by
            # construction order, which both the save and param_names
            # preserve, and verify shapes before accepting
            if len(params) != len(self._params) or \
                    len(aux) != len(self._aux):
                raise ValueError(
                    "checkpoint has %d params / %d aux, trainer has "
                    "%d / %d" % (len(params), len(aux),
                                 len(self._params), len(self._aux)))
            # both sides in structural order: the checkpoint was written
            # in its trainer's param_names order (see save_checkpoint),
            # and this trainer's param_names is the same topological
            # order for the same architecture
            remap = dict(zip(params, self.param_names))
            remap.update(zip(aux, self.aux_names))
            for tables, current in ((params, self._params),
                                    (aux, self._aux)):
                for old in tables:
                    new = remap[old]
                    if new in self._frozen:
                        continue  # batch-geometry zeros, not restored
                    if tuple(tables[old].shape) != \
                            tuple(current[new].shape):
                        raise ValueError(
                            "checkpoint entry %r %s does not match "
                            "trainer entry %r %s"
                            % (old, tables[old].shape, new,
                               current[new].shape))
            params = {remap[n]: a for n, a in params.items()}
            opt = {remap[n]: s for n, s in opt.items()}
            aux = {remap[n]: a for n, a in aux.items()}
        # commit atomically only after every check passed; stateless
        # optimizers (plain sgd) save no opt entries and restore to
        # empty per-param tuples.  Frozen begin-state args keep the
        # CURRENT zeros: the checkpoint may have been written at a
        # different batch size, and they are always zeros anyway.
        self._params = {
            n: (self._params[n] if n in self._frozen
                else self._put(a, self._spec_for(a, n)))
            for n, a in params.items()}
        self._opt_state = {
            n: tuple(self._put(slots[i], self._spec_for(slots[i], n))
                     for i in sorted(slots))
            for n, slots in ((n, opt.get(n, {})) for n in params)}
        self._aux = {n: self._put(a, P()) for n, a in aux.items()}
        self._num_update = num_update

    # -- sync back to gluon parameters --------------------------------------
    def sync_params(self):
        """Write the trained values back into the Block's Parameters
        (gathered to a single device so eager ops can consume them)."""
        import numpy as _np
        params = {p.name: p for p in self.net.collect_params().values()}
        for n, arr in self._params.items():
            if n in self._frozen:
                continue  # zero-filled graph inputs, no Parameter behind
            if self.multi_precision:
                arr = self._opt_state[n][-1]   # f32 master copy
            params[n].data()._data = jnp.asarray(_np.asarray(arr))
        for n, arr in self._aux.items():
            params[n].data()._data = jnp.asarray(_np.asarray(arr))

    @property
    def params(self):
        return self._params


class PipelineTrainer(ParallelTrainer):
    """GPipe pipeline parallelism as a trainer-level peer of DP/TP.

    The net must be a stack (HybridSequential-style ``_children``) of
    ARCHITECTURALLY IDENTICAL blocks — same parameter shapes per block,
    activation shape preserved (the transformer-block case,
    parallel/pipeline.py).  With S = the mesh's ``pp`` axis size and
    C = len(children) (C % S == 0), each pp device owns C/S consecutive
    blocks; per-block parameters are STACKED into (C, ...) leaves
    sharded ``P('pp')``, so weights AND optimizer state live
    stage-local, and the train step streams ``microbatches``
    microbatches through the loop-skew schedule with activations
    hopping stage-to-stage over ``ppermute``.  Composes with a dp axis:
    mesh ``{'dp': d, 'pp': s}`` shards the batch over dp while the
    pipeline runs inside each dp row.

    Everything else (optimizer kernels, LARS, grad clip, LR schedule,
    checkpointed state) is inherited from ParallelTrainer — the stacked
    leaves are ordinary named parameters to the step builder.

    Restriction: blocks with auxiliary state (BatchNorm running stats)
    are rejected — per-stage aux writeback inside the scanned schedule
    is not implemented (reference group2ctx model parallelism has the
    same limitation per placed segment).
    """

    _STACK = "pp:"

    def __init__(self, net, loss, microbatches, **kwargs):
        super().__init__(net, loss, **kwargs)
        if "pp" not in self.mesh.shape:
            raise ValueError(
                "PipelineTrainer needs a mesh with a 'pp' axis "
                "(got axes %r); make_mesh({'dp': d, 'pp': s})"
                % (tuple(self.mesh.axis_names),))
        if "dp" not in self.mesh.shape:
            raise ValueError(
                "PipelineTrainer needs a 'dp' axis for the batch "
                "layout (use {'dp': 1, 'pp': s} for pure pipeline)")
        self.microbatches = int(microbatches)
        if self.shard_params:
            raise ValueError("shard_params (ZeRO over dp) is not "
                             "supported together with the pp stack")
        if self.opt_name in _LARS_NAMES:
            # LARS trust ratios are per named parameter; a (C, ...)
            # stacked leaf would get ONE stack-wide ratio instead of
            # per-layer rates, silently diverging from the sequential
            # trainer
            raise ValueError(
                "LARS-family optimizers are not supported by "
                "PipelineTrainer (stacked block leaves would share one "
                "trust ratio); use sgd/adam/... or per-stage LARS via "
                "the sequential trainer")
        # stacked leaves shard along pp on their leading (block) axis
        self.param_specs.setdefault(r"\App:", P("pp"))

    # -- tracing ----------------------------------------------------------
    def _trace(self, x, y):
        from .. import symbol as sym_mod
        from ..executor import _build_eval
        from .pipeline import pipeline_apply

        children = list(self.net._children.values())
        S = self.mesh.shape["pp"]
        if not children or len(children) % S != 0:
            raise ValueError(
                "net has %d child blocks; need a positive multiple of "
                "the pp axis size %d" % (len(children), S))
        per_stage = len(children) // S

        # trace child 0 once; all blocks share its graph with their own
        # parameter slice
        data = sym_mod.var("data0")
        out0 = children[0](data)
        if out0.list_auxiliary_states():
            raise NotImplementedError(
                "pipeline stages with auxiliary state (BatchNorm "
                "running stats) are not supported")
        child_eval_t = _build_eval(out0, True)
        child_eval_i = _build_eval(out0, False)
        child_args = [a for a in out0.list_arguments() if a != "data0"]

        # local (prefix-stripped) name -> child-0 graph arg name
        def locals_of(block):
            pre = block.prefix
            out = {}
            for p in block.collect_params().values():
                local = p.name[len(pre):] if p.name.startswith(pre) \
                    else p.name
                out[local] = p
            return out

        child0_locals = locals_of(children[0])
        self._local_to_arg = {}
        for arg in child_args:
            pre = children[0].prefix
            local = arg[len(pre):] if arg.startswith(pre) else arg
            if local not in child0_locals:
                raise ValueError(
                    "cannot map child graph arg %r to a block "
                    "parameter" % arg)
            self._local_to_arg[local] = arg
        self._block_locals = sorted(self._local_to_arg)
        self._per_block_params = []
        for i, c in enumerate(children):
            loc = locals_of(c)
            if sorted(loc) != self._block_locals:
                raise ValueError(
                    "block %d parameters %r differ from block 0's %r — "
                    "pipeline stages must be architecturally identical"
                    % (i, sorted(loc), self._block_locals))
            self._per_block_params.append(loc)

        # loss traced on the final activation
        pred = sym_mod.var("pred0")
        label = sym_mod.var("label0")
        loss_sym = self.loss(pred, label)
        loss_eval_t = _build_eval(loss_sym, True)
        loss_eval_i = _build_eval(loss_sym, False)
        extra = [a for a in loss_sym.list_arguments()
                 if a not in ("pred0", "label0")]
        if extra or loss_sym.list_auxiliary_states():
            raise NotImplementedError(
                "parametrized losses are not supported in the pipeline "
                "trainer (loss args %r)" % extra)

        M = self.microbatches
        mesh = self.mesh
        stack = self._STACK
        local_to_arg = self._local_to_arg
        locals_sorted = self._block_locals

        def _pipe_forward(amap, key, training):
            child_eval = child_eval_t if training else child_eval_i
            x_in = amap["data0"]
            B = x_in.shape[0]
            if B % M != 0:
                raise ValueError(
                    "batch %d not divisible by microbatches %d" % (B, M))
            xm = x_in.reshape((M, B // M) + x_in.shape[1:])
            stage_params = {
                loc: amap[stack + loc].reshape(
                    (S, per_stage) + amap[stack + loc].shape[1:])
                for loc in locals_sorted}

            def stage_fn(pslice, xmb):
                # distinct randomness per (stage, sub-block); masks DO
                # repeat across microbatches of one step — a pipeline-
                # semantics caveat vs the sequential trainer
                k_stage = jax.random.fold_in(
                    key, jax.lax.axis_index("pp"))

                def body(h, scanned):
                    pj, j = scanned
                    cam = {local_to_arg[loc]: pj[loc]
                           for loc in locals_sorted}
                    cam["data0"] = h
                    outs, _ = child_eval(
                        cam, {}, jax.random.fold_in(k_stage, j))
                    return outs[0], None
                h, _ = jax.lax.scan(body, xmb,
                                    (pslice, jnp.arange(per_stage)))
                return h

            out = pipeline_apply(stage_fn, stage_params, xm,
                                 axis_name="pp", mesh=mesh,
                                 x_spec=P(None, "dp"))
            return out.reshape((B,) + out.shape[2:])

        def eval_train(amap, aux, key):
            pred_v = _pipe_forward(amap, key, True)
            louts, _ = loss_eval_t(
                {"pred0": pred_v, "label0": amap["label0"]}, {}, key)
            return [louts[0]], {}

        def eval_infer(amap, aux, key):
            pred_v = _pipe_forward(amap, key, False)
            louts, _ = loss_eval_i(
                {"pred0": pred_v, "label0": amap["label0"]}, {}, key)
            return [louts[0]], {}

        def fwd_eval(amap, aux, key):
            return [_pipe_forward(amap, key, False)], {}

        self._eval = eval_train
        self._eval_infer = eval_infer
        self._fwd_eval = fwd_eval
        self.param_names = [stack + loc for loc in locals_sorted]
        self.aux_names = []

    def _gather_state(self, data_shape=None, label_shape=None):
        self._resolve_opt()
        self._frozen = frozenset()
        self._params = {}
        self._opt_state = {}
        for loc in self._block_locals:
            stacked = jnp.stack([blk[loc].data()._data
                                 for blk in self._per_block_params])
            name = self._STACK + loc
            arr, states = self._state_for_array(stacked)
            self._params[name] = self._put(arr, self._spec_for(arr, name))
            self._opt_state[name] = tuple(
                self._put(s, self._spec_for(s, name)) for s in states)
        self._aux = {}
