"""Parallelism package: device mesh, collectives, in-graph data/tensor
parallelism, ring attention (reference counterpart: src/kvstore/ comm
machinery + the parallel training orchestration in python/mxnet/module)."""

from .mesh import (make_mesh, current_mesh, use_mesh, data_parallel_mesh,
                   PartitionSpec, NamedSharding, named_sharding)  # noqa
from . import collectives  # noqa: F401
from .data_parallel import ParallelTrainer  # noqa: F401
from .sequence import (ring_attention_shard,  # noqa: F401
                       sequence_parallel_attention)
from .pipeline import pipeline_apply  # noqa: F401
from .moe import moe_apply  # noqa: F401
from . import multihost  # noqa: F401
