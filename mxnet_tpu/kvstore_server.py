"""Server-role bootstrap (reference: python/mxnet/kvstore_server.py:85 —
if DMLC_ROLE=server the process blocks in RunServer).

Launch:  DMLC_ROLE=server DMLC_PS_ROOT_PORT=9091 DMLC_NUM_WORKER=2 \
         python -m mxnet_tpu.kvstore_server dist_sync
"""

from __future__ import annotations

import os
import sys

from ._kvstore_impl import KVStoreServer


def run_server(kv_type="dist_sync", host=None, port=None, num_workers=None,
               snapshot_prefix=None):
    # The parameter server is a host-side service: aggregation and the
    # server-side optimizer run on CPU (the reference's ps-lite servers
    # are CPU processes), never on the accelerator.
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception as exc:
        # backend already initialized on another platform: the server
        # still works, but say so — a TPU-grabbing server starves the
        # training processes of the accelerator
        import logging
        logging.getLogger(__name__).warning(
            "kvstore server could not pin the cpu backend (%s: %s); "
            "continuing on the default platform",
            type(exc).__name__, exc)
    sync = "async" not in kv_type
    # server s of a multi-server group listens at root port + s
    # (tools/launch.py sets DMLC_SERVER_ID; key sharding lives worker-side)
    server_id = int(os.environ.get("DMLC_SERVER_ID", "0"))
    server = KVStoreServer(
        sync_mode=sync,
        num_workers=num_workers or
        int(os.environ.get("DMLC_NUM_WORKER", "1")),
        host=host or os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
        port=port if port is not None else
        int(os.environ.get("DMLC_PS_ROOT_PORT", "9091")) + server_id,
        server_id=server_id,
        # snapshot_prefix=None defers to MXNET_KVSTORE_SNAPSHOT_PREFIX;
        # with either set, the constructor restores the newest intact
        # snapshot before serving, so worker rejoin pulls resume from
        # committed state after a kill (docs/resilience.md)
        snapshot_prefix=snapshot_prefix)
    server.run()
    return server


if __name__ == "__main__":
    kv_type = sys.argv[1] if len(sys.argv) > 1 else "dist_sync"
    run_server(kv_type)
