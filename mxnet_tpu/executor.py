"""Executor — binds a Symbol to devices + buffers and runs it.

Reference: ``python/mxnet/executor.py`` over ``src/executor/graph_executor.cc``
(SimpleBind :1593, Bind :1624, Forward :64 -> RunOps :1318, Backward :77).

TPU-native design: binding compiles the whole graph (forward, and
forward+vjp for training) into single XLA executables via ``jax.jit``.  The
reference's memory planning (PlanMemory pass), inplace-addto detection, op
segments/bulking and cross-device copy scheduling all collapse into XLA's
compiler — SURVEY.md §7 architecture stance.  Gradients come from one
``jax.vjp`` over the traced graph rather than a constructed backward graph.
``forward``/``backward``/``forward_backward`` mirror the reference's calling
conventions, including grad_req write/add/null and auxiliary-state updates
(BatchNorm moving stats).
"""

from __future__ import annotations

import functools
import logging

import numpy as _np
import jax
import jax.numpy as jnp

from .base import MXNetError, np_dtype
from .context import Context, current_context
from .ndarray import NDArray, zeros as nd_zeros
from .ndarray.ndarray import (_as_nd, _already_placed,
                              _DEVICE_PUT_ELIDED)
from .observability import metrics as _obs_metrics
from .symbol.symbol import Symbol, _infer_shapes

__all__ = ["Executor"]

# module-level ref — observed every legacy train step (no registry
# lookup per dispatch)
_EXEC_STEP_SECONDS = _obs_metrics.histogram(
    "executor_step_dispatch_seconds",
    "host-side latency of one legacy forward+backward dispatch")

# differentiable-leaf suffix for Embedding sparse_grad perturbations
# (train_step diff keys; see ops/sparse_graph.py SparseGradWeight)
_SPARSE_VALS = "!sparse_vals"


def _build_eval(symbol, training):
    """Build the pure graph-evaluation function:
    fn(arg_map, aux_map, key) -> (outputs, aux_updates)."""
    order = symbol._topo()
    out_entries = list(symbol._outputs)

    # ops that consume CSR carriers natively; every other op gets the
    # densified value — the reference's storage-type fallback
    # (infer_graph_attr_pass.cc dispatches to dense kernels with a
    # storage fallback warning)
    csr_aware = ("dot", "cast_storage")

    def fn(arg_map, aux_map, key):
        from .ops.sparse_graph import CsrCarrier
        vals = {}
        aux_updates = {}
        for pos, node in enumerate(order):
            if node.is_var:
                if node.name in arg_map:
                    vals[(id(node), 0)] = arg_map[node.name]
                elif node.name in aux_map:
                    vals[(id(node), 0)] = aux_map[node.name]
                else:
                    raise MXNetError("unbound variable %r" % node.name)
                continue
            op = node.op
            ins = [vals[(id(s), i)] for (s, i) in node.inputs]
            if op.name not in csr_aware:
                ins = [v.todense() if isinstance(v, CsrCarrier) else v
                       for v in ins]
            params = node.params
            if "training" in op.param_names:
                params = dict(params, training=training)
            if op.needs_rng:
                sub = jax.random.fold_in(key, pos)
                out = op.fn(sub, *ins, **params)
            else:
                out = op.fn(*ins, **params)
            if not isinstance(out, tuple):
                out = (out,)
            for i, o in enumerate(out):
                vals[(id(node), i)] = o
            if training and op.aux_states:
                for in_idx, out_idx in op.aux_states.items():
                    src, _ = node.inputs[in_idx]
                    if src.is_var and src.name in aux_map:
                        aux_updates[src.name] = out[out_idx]
        outputs = [vals[(id(n), i)] for (n, i) in out_entries]
        return outputs, aux_updates

    return fn


def _wrap_out(o):
    """Graph output -> NDArray; CSR carriers surface as CSRNDArray."""
    from .ops.sparse_graph import CsrCarrier
    if isinstance(o, CsrCarrier):
        from .ndarray.sparse import CSRNDArray
        return CSRNDArray(NDArray(o.data), NDArray(o.indices),
                          NDArray(o.indptr), o.shape)
    return NDArray(o)


class Executor:
    """A bound computation graph."""

    def __init__(self, symbol, ctx, arg_dict, grad_dict, aux_dict,
                 grad_req, group2ctx=None):
        self._symbol = symbol
        self._ctx = ctx or current_context()
        self._group2ctx = dict(group2ctx or {})
        self.arg_dict = arg_dict
        self.grad_dict = grad_dict
        self.aux_dict = aux_dict
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()
        if isinstance(grad_req, str):
            grad_req = {n: grad_req for n in self._arg_names}
        elif isinstance(grad_req, (list, tuple)):
            grad_req = dict(zip(self._arg_names, grad_req))
        self._grad_req = {n: grad_req.get(n, "null")
                          for n in self._arg_names}
        # CSR args flow through the traced graph as (values, indices,
        # indptr) carriers (ops/sparse_graph.py); gradients THROUGH a
        # csr input are not computed (the reference likewise has no
        # backward for its csr-lhs dot kernels) — a blanket grad_req
        # simply excludes them
        from .ndarray.sparse import CSRNDArray
        for n, a in arg_dict.items():
            if isinstance(a, CSRNDArray):
                self._grad_req[n] = "null"
        self._grad_names = [n for n in self._arg_names
                            if self._grad_req[n] != "null" and
                            grad_dict.get(n) is not None]
        # Embedding(sparse_grad=True): deliver the weight grad as
        # row_sparse (ids, rows) pairs instead of a dense (vocab, dim)
        # buffer — see ops/sparse_graph.py SparseGradWeight
        self._sparse_embeds = {}
        self._sparse_embed_nodes = {}
        for node in symbol._topo():
            if node.is_var or node.op.name != "Embedding":
                continue
            sg = node.params.get("sparse_grad", False)
            if isinstance(sg, str):
                sg = sg in ("True", "true", "1")
            if not sg:
                continue
            wsrc, _ = node.inputs[1]
            dsrc, _ = node.inputs[0]
            if self._grad_req.get(wsrc.name, "null") == "null":
                continue
            if not (wsrc.is_var and dsrc.is_var):
                raise MXNetError(
                    "Embedding sparse_grad=True needs variable data and "
                    "weight inputs (got computed inputs for %r)"
                    % node.name)
            if self._grad_req[wsrc.name] == "add":
                raise MXNetError(
                    "grad_req='add' is unsupported for sparse_grad "
                    "Embedding weights (rsp pair grads are rebuilt each "
                    "backward)")
            if wsrc.name in self._sparse_embeds:
                raise MXNetError(
                    "weight %r feeds multiple sparse_grad Embedding "
                    "nodes; share a dense-grad weight or split it"
                    % wsrc.name)
            self._sparse_embeds[wsrc.name] = (
                dsrc.name, int(node.params.get("output_dim")))
            self._sparse_embed_nodes[wsrc.name] = node
        # swap the grad buffer for an rsp container ONCE at bind so the
        # handle a caller grabs (args_grad, the C ABI's arg_grads) stays
        # aliased across backwards — writeback mutates it in place.
        # Until the first backward it holds one zero row at an
        # out-of-bounds id (todense == zeros).
        if self._sparse_embeds:
            from .ndarray.sparse import RowSparseNDArray
            for n in list(self._sparse_embeds):
                if n in self._grad_names:
                    dense = grad_dict[n]
                    dim = self._sparse_embeds[n][1]
                    grad_dict[n] = RowSparseNDArray(
                        NDArray(jnp.zeros((1, dim), dense.dtype)),
                        NDArray(jnp.full((1,), dense.shape[0],
                                         jnp.int32)),
                        tuple(dense.shape))
        if self._sparse_embeds:
            # a sparse-grad weight must feed ONLY its Embedding node:
            # train_step wraps it in a SparseGradWeight carrier, which
            # other ops (e.g. a tied output projection) cannot consume.
            # The exemption is the SPECIFIC registered node — a weight
            # shared with a second Embedding (sparse or not) must fail
            # here too, not surface as a trace-time shape error
            for node in symbol._topo():
                if node.is_var:
                    continue
                for i, (src, _) in enumerate(node.inputs):
                    if src.is_var and src.name in self._sparse_embeds \
                            and not (node is self._sparse_embed_nodes[
                                src.name] and i == 1):
                        raise MXNetError(
                            "weight %r has sparse_grad=True but is also "
                            "consumed by %r (%s); weight tying requires "
                            "a dense gradient" % (src.name, node.name,
                                                  node.op.name))
        self.outputs = []
        # the PRNG key must live on this executor's device: under a
        # two-platform session (cpu-vs-tpu consistency runs) a
        # default-device key mixed with ctx-placed args is a jit error
        self._key = jax.device_put(jax.random.PRNGKey(0),
                                   self._ctx.jax_device)
        self._fwd_jit = {}
        self._fused_jit = None
        self._monitor = None

        eval_train = _build_eval(symbol, True)
        eval_infer = _build_eval(symbol, False)

        def fwd(training, arg_map, aux_map, key):
            f = eval_train if training else eval_infer
            return f(arg_map, aux_map, key)

        self._eval_train = eval_train
        self._eval_infer = eval_infer
        self._jit_infer = jax.jit(
            lambda arg_map, aux_map, key: eval_infer(arg_map, aux_map, key))
        self._jit_train = jax.jit(
            lambda arg_map, aux_map, key: eval_train(arg_map, aux_map, key))

        grad_names = self._grad_names
        sparse_embeds = {n: v for n, v in self._sparse_embeds.items()
                         if n in grad_names}

        def train_step(arg_map, aux_map, key, out_cots):
            diff = {n: arg_map[n] for n in grad_names
                    if n not in sparse_embeds}
            for w, (dname, dim) in sparse_embeds.items():
                # the differentiable leaf is the zero per-occurrence
                # perturbation; the weight itself stays non-diff so no
                # dense (vocab, dim) cotangent is ever formed
                ids = arg_map[dname]
                diff[w + _SPARSE_VALS] = jnp.zeros(ids.shape + (dim,),
                                                   arg_map[w].dtype)
            rest = {n: v for n, v in arg_map.items() if n not in diff}

            def run(d):
                amap = dict(rest)
                for n, v in d.items():
                    if n.endswith(_SPARSE_VALS):
                        from .ops.sparse_graph import SparseGradWeight
                        w = n[:-len(_SPARSE_VALS)]
                        amap[w] = SparseGradWeight(rest[w], v)
                    else:
                        amap[n] = v
                outs, auxu = eval_train(amap, aux_map, key)
                return outs, auxu

            (outs, auxu), vjp_fn = jax.vjp(lambda d: run(d), diff)
            cots = [c if c is not None else jnp.ones_like(o)
                    for c, o in zip(out_cots, outs)]
            cots = [c.astype(o.dtype) if c.dtype != o.dtype else c
                    for c, o in zip(cots, outs)]
            zero_aux = jax.tree_util.tree_map(jnp.zeros_like, auxu)
            grads = vjp_fn((cots, zero_aux))[0]
            # canonicalize rsp grads in-graph: unique sorted rows with
            # summed values (row-wise optimizer kernels require
            # duplicate-free ids; tail slots pad with an out-of-bounds
            # id that every .at[] consumer drops)
            from .ops.sparse_graph import dedup_rsp_pairs
            for w, (dname, dim) in sparse_embeds.items():
                vals = grads.pop(w + _SPARSE_VALS)
                grads[w] = dedup_rsp_pairs(arg_map[dname], vals,
                                           arg_map[w].shape[0])
            return outs, auxu, grads

        self._jit_train_step = jax.jit(train_step)
        # unjitted core kept for nesting inside the fused
        # forward+backward+update program (init_fused_step)
        self._train_step_fn = train_step

        if self._group2ctx:
            self._init_grouped()

    def _init_grouped(self):
        """Replace the whole-graph jits with the segment-chained
        model-parallel path (see grouped_executor.py)."""
        if self._sparse_embeds:
            raise MXNetError(
                "Embedding sparse_grad=True is not supported together "
                "with group2ctx model parallelism")
        from .grouped_executor import build_grouped_eval
        sym = self._symbol
        aux_names = self._aux_names
        run_t, back_t, segs = build_grouped_eval(
            sym, self._group2ctx, self._ctx, True, aux_names)
        run_i, _, _ = build_grouped_eval(
            sym, self._group2ctx, self._ctx, False, aux_names)
        self._segments = segs
        grad_names = self._grad_names

        def jit_infer(arg_map, aux_map, key):
            outs, auxu, _ = run_i(arg_map, aux_map, key, False)
            return outs, auxu

        def jit_train(arg_map, aux_map, key):
            outs, auxu, _ = run_t(arg_map, aux_map, key, False)
            return outs, auxu

        def train_step(arg_map, aux_map, key, out_cots):
            outs, auxu, vjps = run_t(arg_map, aux_map, key, True)
            cots = [c.astype(o.dtype) if c.dtype != o.dtype else c
                    for c, o in zip(out_cots, outs)]
            all_grads = back_t(vjps, cots)
            grads = {}
            for n in grad_names:
                g = all_grads.get(n)
                if g is None:
                    g = jnp.zeros_like(arg_map[n])
                grads[n] = g
            return outs, auxu, grads

        self._jit_infer = jit_infer
        self._jit_train = jit_train
        self._jit_train_step = train_step
        # segment-chained evaluation is not one pure program; the fused
        # single-program step cannot be built on top of it
        self._train_step_fn = None

    def init_fused_step(self, tree_update_fn, guard_nonfinite=False):
        """Build the fused train step: forward + VJP + optimizer update
        in ONE donated ``jax.jit`` — weights and optimizer state stay
        device-resident and step N+1 chains on step N's donated
        buffers (no per-parameter host dispatch; the TVM/CUDA-Graph
        whole-step-capture idea applied at the XLA level).

        ``tree_update_fn(grads, params, state, lrs, wds, ts)`` is the
        pure tree-level optimizer sweep (optimizer/tree_opt.py).
        Signature of the returned callable::

            fused(params, rest, aux_map, base_key, opt_state, lrs,
                  wds, ts, step) -> (outs, new_aux, new_params,
                                     new_opt_state[, skipped])

        *params* holds only the UPDATABLE args (donated); data/labels/
        fixed params ride in *rest* undonated so caller-owned batch
        buffers stay valid.  *ts* carries the per-name update counts;
        *step* is the scalar step the PRNG key is folded with in-graph,
        so not even a key split dispatches per step.

        With *guard_nonfinite*, one fused ``isfinite`` reduction over
        the loss outputs + gradient tree decides in-graph whether the
        update applies: a non-finite step returns params, optimizer
        state AND aux (BatchNorm stats) bit-identical, plus a trailing
        int32 ``skipped`` flag — still the same single program, no
        recompile (see docs/resilience.md)."""
        if self._train_step_fn is None:
            raise MXNetError(
                "the fused train step is not supported with group2ctx "
                "model parallelism (segment-chained execution)")
        core = self._train_step_fn
        n_outs = len(self._symbol._outputs)
        from . import profiler as _prof
        from .optimizer import tree_opt as _tree_opt

        def fused_step(params, rest, aux_map, base_key, opt_state, lrs,
                       wds, ts, step):
            # the Python body only runs at trace time — this IS the
            # compile counter (cached executions bump nothing)
            _prof.bump_counter(  # graftlint: disable=JG003
                "fused_step_compiles")  # trace-time-only on purpose
            key = jax.random.fold_in(base_key, step)
            arg_map = dict(rest)
            arg_map.update(params)
            outs, auxu, grads = core(arg_map, aux_map, key,
                                     [None] * n_outs)
            new_params, new_state = tree_update_fn(
                grads, params, opt_state, lrs, wds, ts)
            new_aux = dict(aux_map)
            new_aux.update(auxu)
            if guard_nonfinite:
                bad = jnp.logical_or(_tree_opt.nonfinite_any(outs),
                                     _tree_opt.nonfinite_any(grads))
                new_params = _tree_opt.select_tree(bad, params,
                                                   new_params)
                new_state = _tree_opt.select_tree(bad, opt_state,
                                                  new_state)
                new_aux = _tree_opt.select_tree(bad, aux_map, new_aux)
                return (outs, new_aux, new_params, new_state,
                        bad.astype(jnp.int32))
            return outs, new_aux, new_params, new_state

        from .ops.registry import supports_donation
        # donate weights + optimizer state (argnums 0 and 4)
        donate = (0, 4) if supports_donation() else ()
        # the caller owns the program (Module keeps it in _fused["fn"]
        # and rebuilds on hyper-param mutation) — not stored here
        return jax.jit(fused_step, donate_argnums=donate)

    # -- binding constructors ---------------------------------------------
    @staticmethod
    def _simple_bind(symbol, ctx, grad_req, type_dict, shape_kwargs,
                     shared_exec=None, group2ctx=None):
        shapes = {k: tuple(v) for k, v in shape_kwargs.items()}
        _, var_sh = _infer_shapes(symbol, shapes)
        type_dict = type_dict or {}
        arg_dict = {}
        for n in symbol.list_arguments():
            dt = type_dict.get(n, "float32")
            if shared_exec is not None and n in shared_exec.arg_dict and \
                    tuple(shared_exec.arg_dict[n].shape) == var_sh[n]:
                arg_dict[n] = shared_exec.arg_dict[n]
            else:
                arg_dict[n] = nd_zeros(var_sh[n], ctx=ctx, dtype=dt)
        aux_dict = {}
        for n in symbol.list_auxiliary_states():
            if shared_exec is not None and n in shared_exec.aux_dict and \
                    tuple(shared_exec.aux_dict[n].shape) == var_sh[n]:
                aux_dict[n] = shared_exec.aux_dict[n]
            else:
                aux_dict[n] = nd_zeros(var_sh[n], ctx=ctx)
        if isinstance(grad_req, str):
            reqs = {n: grad_req for n in arg_dict}
        elif isinstance(grad_req, (list, tuple)):
            reqs = dict(zip(symbol.list_arguments(), grad_req))
        else:
            reqs = {n: grad_req.get(n, "null") for n in arg_dict}
        grad_dict = {n: nd_zeros(var_sh[n], ctx=ctx,
                                 dtype=type_dict.get(n, "float32"))
                     for n in arg_dict if reqs.get(n, "null") != "null"}
        return Executor(symbol, ctx, arg_dict, grad_dict, aux_dict, reqs,
                        group2ctx=group2ctx)

    @staticmethod
    def _bind(symbol, ctx, args, args_grad, grad_req, aux_states,
              group2ctx=None):
        arg_names = symbol.list_arguments()
        if isinstance(args, (list, tuple)):
            arg_dict = dict(zip(arg_names, [_as_nd(a) for a in args]))
        else:
            arg_dict = {k: _as_nd(v) for k, v in (args or {}).items()}
        if args_grad is None:
            grad_dict = {}
        elif isinstance(args_grad, (list, tuple)):
            grad_dict = dict(zip(arg_names, [_as_nd(g) if g is not None
                                             else None for g in args_grad]))
        else:
            grad_dict = {k: _as_nd(v) for k, v in args_grad.items()}
        grad_dict = {k: v for k, v in grad_dict.items() if v is not None}
        aux_names = symbol.list_auxiliary_states()
        if isinstance(aux_states, (list, tuple)):
            aux_dict = dict(zip(aux_names, [_as_nd(a) for a in aux_states]))
        else:
            aux_dict = {k: _as_nd(v) for k, v in (aux_states or {}).items()}
        for n in aux_names:
            if n not in aux_dict:
                raise MXNetError("missing auxiliary state %r" % n)
        return Executor(symbol, ctx, arg_dict, grad_dict, aux_dict, grad_req,
                        group2ctx=group2ctx)

    # -- properties --------------------------------------------------------
    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._arg_names]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self._arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self._aux_names]

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    # -- execution ---------------------------------------------------------
    def _arg_map(self):
        from .ndarray.sparse import CSRNDArray
        from .ops.sparse_graph import CsrCarrier
        out = {}
        for n, a in self.arg_dict.items():
            if isinstance(a, CSRNDArray):
                out[n] = CsrCarrier(a._data, a._aux[0], a._aux[1],
                                    a.shape)
            else:
                out[n] = a._data
        return out

    def _aux_map(self):
        return {n: a._data for n, a in self.aux_dict.items()}

    def rng_state(self):
        """The executor's PRNG base key as plain ints (JSON-safe).

        This is the key the fused step folds the update count into
        in-graph (``fold_in(base_key, step)``), and the key the eager
        paths split per call — restoring it (plus the optimizer's
        update counts) makes dropout masks after a resume bit-identical
        to the uninterrupted run."""
        import numpy as _onp
        raw = _onp.asarray(jax.device_get(self._key))
        return {"shape": list(raw.shape),
                "data": [int(v) for v in raw.ravel().tolist()]}

    def set_rng_state(self, state):
        import numpy as _onp
        raw = _onp.asarray(state["data"], dtype=_onp.uint32).reshape(
            state["shape"])
        self._key = jax.device_put(jnp.asarray(raw),
                                   self._ctx.jax_device)

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _place(self, arr):
        """Move an incoming array onto this executor's device (the
        reference's executor_group copies batch slices per ctx,
        executor_group.py:436).  An array already COMMITTED here — a
        DevicePrefetcher ring batch, or a slice of one — skips the put
        entirely (counted via ``device_put_elided_total``); an
        uncommitted on-device array still routes through device_put so
        its committedness can't flip the fused program's jit cache key
        between steps (the graftsan recompile lesson)."""
        import jax as _jax
        dev = self._ctx.jax_device
        if _already_placed(arr, dev):
            _DEVICE_PUT_ELIDED.inc()
            return arr
        return _jax.device_put(arr, dev)

    def forward(self, is_train=False, **kwargs):
        """Run the graph (reference: executor.py forward:114)."""
        for k, v in kwargs.items():
            if k in self.arg_dict:
                self.arg_dict[k]._data = self._place(_as_nd(v)._data.astype(
                    self.arg_dict[k].dtype))
            else:
                raise MXNetError("unknown forward argument %r" % k)
        from .runtime import engine as _engine
        key = self._next_key()
        if not _engine.bulk_enabled(is_train):
            # bulking disabled: per-node eager dispatch (the reference's
            # non-bulk engine path, graph_executor.cc:1187) — every op
            # runs as its own dispatch, fully debuggable
            outs, auxu = self._eval_per_node(self._arg_map(),
                                             self._aux_map(), key,
                                             is_train)
        else:
            fn = self._jit_train if is_train else self._jit_infer
            from . import profiler as _prof
            _prof.bump_counter("executor_dispatches")
            outs, auxu = fn(self._arg_map(), self._aux_map(), key)
        if is_train:
            # keep the key: backward() must replay the same stochastic
            # masks (Dropout etc.) that produced these outputs
            self._pending = (self._arg_map(), self._aux_map(), key)
        for n, v in auxu.items():
            self.aux_dict[n]._data = v
        self.outputs = [_wrap_out(o) for o in outs]
        if self._monitor is not None:
            if getattr(self, "_monitor_all", False):
                taps = self._monitor_taps(self._arg_map(),
                                          self._aux_map(), key, is_train)
                for name in sorted(taps):
                    self._monitor(name, NDArray(taps[name]))
            else:
                for name, val in zip(self._symbol.list_outputs(),
                                     self.outputs):
                    self._monitor(name, val)
        return self.outputs

    def backward(self, out_grads=None, is_train=True):
        """Gradients via whole-graph vjp (reference: backward:155 over the
        constructed gradient graph)."""
        self._run_train_step(out_grads, use_pending=True)

    def forward_backward(self, out_grads=None, **kwargs):
        """Fused forward+backward in one XLA program — the fast path the
        Module training loop uses (no double forward)."""
        for k, v in kwargs.items():
            if k in self.arg_dict:
                self.arg_dict[k]._data = self._place(_as_nd(v)._data.astype(
                    self.arg_dict[k].dtype))
        self._run_train_step(out_grads, use_pending=False)
        return self.outputs

    def _run_train_step(self, out_grads, use_pending):
        if out_grads is None:
            cots = [None] * len(self._symbol._outputs)
        elif isinstance(out_grads, NDArray):
            cots = [out_grads._data]
        else:
            cots = [g._data if g is not None else None for g in out_grads]
        if use_pending and getattr(self, "_pending", None) is not None:
            arg_map, aux_map, key = self._pending
            self._pending = None
        else:
            arg_map, aux_map = self._arg_map(), self._aux_map()
            key = self._next_key()
        # None cotangents must be materialized as ones for jit
        from . import profiler as _prof
        import time as _time
        _prof.bump_counter("executor_dispatches")
        t0 = _time.perf_counter()
        outs, auxu, grads = self._jit_train_step(
            arg_map, aux_map, key,
            _materialize(cots, self, arg_map, aux_map))
        # host-side latency to issue the legacy (non-fused)
        # forward+backward program — the fused path's histogram twin,
        # so an A/B of the two update paths is one scrape away
        _EXEC_STEP_SECONDS.observe(_time.perf_counter() - t0)
        for n, v in auxu.items():
            self.aux_dict[n]._data = v
        self.outputs = [_wrap_out(o) for o in outs]
        for n in self._grad_names:
            if n in self._sparse_embeds:
                # rsp pair grad, deduped to unique sorted rows
                # in-graph; the container object is stable from bind
                # time (caller handles alias it) — update in place
                ids, vals = grads[n]
                dst = self.grad_dict[n]
                dst._data = vals
                dst._aux[0] = ids
                continue
            g = grads[n]
            dst = self.grad_dict[n]
            g = g.astype(dst.dtype) if g.dtype != dst.dtype else g
            if self._grad_req[n] == "add":
                dst._data = dst._data + g
            else:
                dst._data = g

    # -- utilities ---------------------------------------------------------
    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in (arg_params or {}).items():
            if k in self.arg_dict:
                v.copyto(self.arg_dict[k])
            elif not allow_extra_params:
                raise MXNetError("unknown argument %r" % k)
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                v.copyto(self.aux_dict[k])
            elif not allow_extra_params:
                raise MXNetError("unknown aux state %r" % k)

    def reshape(self, partial_shaping=False, allow_up_sizing=False,
                **kwargs):
        """Re-bind with new shapes (reference: executor.py reshape:372);
        recompilation is per-shape cached by jit."""
        shapes = {}
        for n, a in self.arg_dict.items():
            shapes[n] = kwargs.get(n, a.shape)
        ex = Executor._simple_bind(self._symbol, self._ctx, self._grad_req,
                                   None, shapes)
        for n, a in self.arg_dict.items():
            if tuple(ex.arg_dict[n].shape) == tuple(a.shape):
                ex.arg_dict[n] = a
        for n, a in self.aux_dict.items():
            if tuple(ex.aux_dict[n].shape) == tuple(a.shape):
                ex.aux_dict[n] = a
        return ex

    def _eval_per_node(self, arg_map, aux_map, key, is_train):
        """Non-bulk execution: the same walk _build_eval traces, but
        dispatched eagerly op by op (reference: non-bulk engine ops,
        graph_executor.cc:1187-1215 / MXEngineSetBulkSize(0))."""
        fn = self._eval_train if is_train else self._eval_infer
        return fn(arg_map, aux_map, key)

    def set_monitor_callback(self, callback, monitor_all=False):
        """Install a per-op output tap (reference:
        MXExecutorSetMonitorCallback / graph_executor.cc:104,1295).
        With monitor_all, forward also reports every interior node's
        outputs, not just the graph outputs."""
        self._monitor = callback
        self._monitor_all = monitor_all
        self._jit_monitor = {}

    def _monitor_taps(self, arg_map, aux_map, key, is_train):
        """Evaluate the graph returning {tap_name: value} for every op
        node output (compiled once per training mode)."""
        if self._jit_monitor.get(is_train) is None:
            order = self._symbol._topo()

            def tap_eval(arg_map, aux_map, key):
                vals = {}
                taps = {}
                for pos, node in enumerate(order):
                    if node.is_var:
                        vals[(id(node), 0)] = arg_map.get(
                            node.name, aux_map.get(node.name))
                        continue
                    op = node.op
                    ins = [vals[(id(s), i)] for (s, i) in node.inputs]
                    params = node.params
                    if "training" in op.param_names:
                        params = dict(params, training=is_train)
                    if op.needs_rng:
                        out = op.fn(jax.random.fold_in(key, pos), *ins,
                                    **params)
                    else:
                        out = op.fn(*ins, **params)
                    if not isinstance(out, tuple):
                        out = (out,)
                    for i, o in enumerate(out):
                        vals[(id(node), i)] = o
                    n_vis = op.n_visible(node.params)
                    for i in range(n_vis):
                        nm = node.name + ("_output" if n_vis == 1
                                          else "_output%d" % i)
                        taps[nm] = out[i]
                return taps

            self._jit_monitor[is_train] = jax.jit(tap_eval)
        return self._jit_monitor[is_train](arg_map, aux_map, key)

    def debug_str(self):
        lines = ["Symbol outputs: %s" % self._symbol.list_outputs()]
        for node in self._symbol._topo():
            kind = "var" if node.is_var else node.op.name
            lines.append("%s %s <- %s" % (kind, node.name,
                                          [s.name for s, _ in node.inputs]))
        return "\n".join(lines)


def _materialize(cots, ex, arg_map, aux_map):
    """Replace None head-cotangents with ones of the right shape (the
    reference allows backward() without out_grads for loss heads)."""
    if all(c is not None for c in cots):
        return cots
    # cheap shape inference: run eval_shape on the infer function
    try:
        shapes = jax.eval_shape(ex._eval_infer, arg_map, aux_map,
                                ex._key)[0]
    except Exception as e:
        # fall back to a real forward for the shapes, but keep the
        # eval_shape failure diagnosable instead of eating it
        logging.getLogger(__name__).debug(
            "eval_shape failed in _materialize (%s: %s); falling back "
            "to an executed forward pass", type(e).__name__, e)
        outs, _ = ex._jit_infer(arg_map, aux_map, ex._key)
        shapes = outs
    dev = ex._ctx.jax_device
    return [c if c is not None
            else jax.device_put(jnp.ones(s.shape, s.dtype), dev)
            for c, s in zip(cots, shapes)]
