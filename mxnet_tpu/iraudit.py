"""Bridge to the graftir static IR auditor (tools/graftir).

Production code never imports ``tools.graftir`` directly — the AOT
program producers (the fused train step, ``CompiledPredictor``,
``DecodeEngine``, the quantize gate) call :func:`audit` here with
their lowered StableHLO text and their declarations (donation
promise, dtype policy, bucket geometry, program budget), and the
bridge falls through to a no-op unless ``MXNET_IR_AUDIT`` is set.

The off-path cost is one environment read per *program build* (not
per dispatch) and zero extra lowering: every hook sits on a path that
already has — or is about to produce — the lowered text.

Two consumers:

* **production** (``MXNET_IR_AUDIT=1``): each registered program is
  audited immediately against the graftir rules + committed baseline;
  new findings are logged, counted
  (``mxnet_ir_audit_findings_total``) and evented (``iraudit``
  category).  The bridge keeps the per-process program list so GI005
  (program-count budget) sees request-path compiles that sneak in
  after warmup.
* **the representative-set builder** (``tools/graftir/programs.py``):
  :func:`collect` redirects registrations into a list instead of
  auditing, so ``python -m tools.graftir`` exercises the *same
  producer hooks* CI ships.

Like the graftsan bridge, the implementation lives in the repo's
``tools/`` tree; enabling the knob without that tree raises a clear
error instead of silently auditing nothing.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading

__all__ = ["enabled", "audit", "collect"]

_COLLECT = None          # active collector list (forces enabled())
_SEEN = []               # per-process audited programs (GI005 groups)
_LOCK = threading.Lock()
_FINDINGS_TOTAL = None   # lazy counter
_LOG = logging.getLogger("mxnet_tpu.iraudit")


def enabled():
    """Is the IR audit on?  (read from env each call, like MXNET_SAN)"""
    if _COLLECT is not None:
        return True
    raw = os.environ.get("MXNET_IR_AUDIT", "").strip().lower()
    return bool(raw) and raw not in ("0", "off", "none", "false")


def _graftir():
    """Import tools.graftir (repo-root layout) with a clear failure."""
    try:
        import tools.graftir as g
        return g
    except ImportError:
        import sys
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if root not in sys.path and \
                os.path.isdir(os.path.join(root, "tools", "graftir")):
            sys.path.insert(0, root)
            import tools.graftir as g
            return g
        raise RuntimeError(
            "MXNET_IR_AUDIT is set but the graftir auditor "
            "(tools/graftir) is not importable — run from a repo "
            "checkout, or unset MXNET_IR_AUDIT")


@contextlib.contextmanager
def collect():
    """Redirect program registrations into a list (yielded) instead of
    auditing them — the representative-set builder's capture hook.
    Forces :func:`enabled` True for the duration."""
    global _COLLECT
    prev, _COLLECT = _COLLECT, []
    try:
        yield _COLLECT
    finally:
        _COLLECT = prev


def reset_seen():
    """Drop the per-process GI005 program ledger (tests)."""
    with _LOCK:
        del _SEEN[:]


def audit(subsystem, name, text, **decl):
    """Register one lowered program for audit.

    *decl* carries the producer's declarations (``model=``,
    ``donated=``, ``dtype_policy=``, ``hot_path=``, ``bucket_rows=``,
    ``natural_rows=``, ``budget=``, ``suppress=``).  Returns the
    findings list (empty when clean), the collected Program in
    collector mode, or None when the audit is off.  Never raises on
    rule findings — the audit observes, CI gates."""
    if not enabled():
        return None
    g = _graftir()
    prog = g.Program(subsystem, name, text, **decl)
    if _COLLECT is not None:
        _COLLECT.append(prog)
        return prog
    with _LOCK:
        _SEEN.append(prog)
        group = [p for p in _SEEN
                 if (p.subsystem, p.model) == (subsystem, prog.model)]
    # per-program rules on the new program; the group-count rule over
    # everything this process lowered for the same (subsystem, model)
    # — a request-path compile past the warm set trips GI005 here
    _, findings = g.audit_programs(
        [prog], rules=["GI001", "GI002", "GI003", "GI004"])
    _, group_findings = g.audit_programs(group, rules=["GI005"])
    findings = list(findings) + list(group_findings)
    new = [f for f in findings if f.status == "new"]
    _count(len(new))
    from .observability import events as _obs_events
    _obs_events.emit("iraudit", kind="audit", program=prog.key(),
                     sha=prog.sha(), findings=len(findings),
                     new=len(new),
                     rules=sorted({f.rule for f in new}))
    for f in new:
        _LOG.warning("graftir: %r", f)
    return findings


def _count(n):
    global _FINDINGS_TOTAL
    if _FINDINGS_TOTAL is None:
        from .observability import metrics as _metrics
        _FINDINGS_TOTAL = _metrics.counter(
            "mxnet_ir_audit_findings_total",
            "new graftir findings surfaced by the MXNET_IR_AUDIT "
            "production hook")
    if n:
        _FINDINGS_TOTAL.inc(n)
