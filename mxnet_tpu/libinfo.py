"""Library discovery + version info (reference: python/mxnet/libinfo.py
— find_lib_path locating libmxnet.so for the ctypes layer).

Here the compute path needs no native library, but the optional C ABI
shims (predict + NDArray) do exist; ``find_lib_path`` locates them for
FFI consumers and tooling.
"""

from __future__ import annotations

import os

from . import __version__  # noqa: F401  (reference re-exports it here)

__all__ = ["find_lib_path", "find_include_path", "__version__"]

_LIBS = ("libmxtpu_nd.so", "libmxtpu_predict.so")


def _candidates():
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(here)
    env = os.environ.get("MXNET_LIBRARY_PATH")
    roots = ([env] if env else []) + [
        os.path.join(repo, "build"),
        os.path.join(here, "build"),
    ]
    return roots


def find_lib_path(optional=False):
    """Paths of the built C ABI libraries (reference:
    libinfo.py:find_lib_path; raises unless *optional* when none are
    built)."""
    found = []
    # upstream convention: MXNET_LIBRARY_PATH may name the library FILE
    # itself, not just a directory to search
    env = os.environ.get("MXNET_LIBRARY_PATH")
    if env and os.path.isfile(env):
        found.append(env)
    for root in _candidates():
        for lib in _LIBS:
            p = os.path.join(root, lib)
            if os.path.exists(p) and p not in found:
                found.append(p)
    if not found and not optional:
        raise RuntimeError(
            "native C ABI libraries not built — run `make -C src/capi` "
            "(searched: %s)" % (_candidates(),))
    return found


def find_include_path():
    """Path of the C ABI headers (reference: find_include_path)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    inc = os.path.join(repo, "include")
    if not os.path.isdir(os.path.join(inc, "mxtpu")):
        raise RuntimeError("include/mxtpu headers not found at %r" % inc)
    return inc
