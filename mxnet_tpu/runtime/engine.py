"""Engine control surface.

The reference exposes a handful of engine controls to Python
(``MXNDArrayWaitAll``, ``MXEngineSetBulkSize``, engine type selection via
``MXNET_ENGINE_TYPE`` — ``src/engine/engine.cc:32-48``) and propagates op
exceptions along the dependency chain to the next sync point
(``threaded_engine.h:179-180,256-257``; docs/architecture/
exception_handling.md).  On TPU the device scheduler *is* XLA+PJRT async
dispatch, so these become shims with the same observable semantics:

- ``wait_all`` blocks until outstanding device work is done AND rethrows
  any exception recorded by host-side async components (prefetch threads,
  kvstore heartbeats, dataloader workers) — the dependency-chain
  rethrow-at-sync contract.
- ``naive_mode`` forces synchronous execution after every op (the
  NaiveEngine debugging escape hatch), selectable via
  ``MXNET_ENGINE_TYPE=NaiveEngine``.
- ``set_bulk_size(0)`` disables whole-graph bulking: executors evaluate
  per node (the monitor path) instead of one fused XLA program
  (reference: bulk segments, graph_executor.cc:1187-1215).
"""

from __future__ import annotations

import contextlib
import threading

import jax

from ..config import get_env
from .. import sanitizer as _san

_naive = None   # None = consult MXNET_ENGINE_TYPE; bool = explicit
_bulk_size = None  # None = consult MXNET_EXEC_BULK_EXEC_*; int override
_exc_lock = _san.lock(label="engine._exc_lock")
_pending_exceptions = []


def wait_all():
    """Block until all async device work has completed, then rethrow
    the first exception recorded by async host components (reference:
    Engine::WaitForAll / MXNDArrayWaitAll + exception chain rethrow)."""
    try:
        jax.effects_barrier()
    except Exception as exc:
        # older jax without effects_barrier (or a backend that rejects
        # it): fall back to a trivial device sync, but keep the reason
        # diagnosable — a real dispatch failure surfacing here must not
        # vanish
        import logging
        logging.getLogger(__name__).debug(
            "effects_barrier unavailable (%s: %s); falling back to "
            "block_until_ready", type(exc).__name__, exc)
        jax.block_until_ready(jax.numpy.zeros(()))
    rethrow_pending()


def record_exception(exc):
    """Register an exception from an async host component (prefetch
    thread, kvstore heartbeat, dataloader worker); it rethrows at the
    next sync point — the reference's var-exception propagation
    (threaded_engine.h:256)."""
    with _exc_lock:
        _pending_exceptions.append(exc)


def rethrow_pending():
    with _exc_lock:
        if not _pending_exceptions:
            return
        exc = _pending_exceptions.pop(0)
    raise exc


def clear_exceptions():
    with _exc_lock:
        _pending_exceptions.clear()


def is_naive():
    if _naive is not None:
        return _naive
    return get_env("MXNET_ENGINE_TYPE") == "NaiveEngine"


def set_naive(flag):
    """Enable synchronous (NaiveEngine-style) execution for debugging."""
    global _naive
    _naive = bool(flag)


@contextlib.contextmanager
def naive_mode():
    global _naive
    prev = _naive
    set_naive(True)
    try:
        yield
    finally:
        _naive = prev


def set_bulk_size(size):
    """0 disables graph bulking (per-node execution); >0 restores the
    whole-graph program (reference: MXEngineSetBulkSize).  Returns the
    previous override (None = env-driven default)."""
    global _bulk_size
    prev = _bulk_size
    _bulk_size = int(size)
    return prev


def bulk_enabled(is_train=True):
    """Should executors compile the whole graph as one program?"""
    if _bulk_size is not None:
        return _bulk_size > 0
    return get_env("MXNET_EXEC_BULK_EXEC_TRAIN" if is_train
                   else "MXNET_EXEC_BULK_EXEC_INFERENCE")


@contextlib.contextmanager
def bulk(size):
    """Scoped bulk-size override (reference: mx.engine bulk context)."""
    global _bulk_size
    prev = _bulk_size
    _bulk_size = int(size)
    try:
        yield
    finally:
        # restore the raw previous state, including the env-driven
        # None sentinel — a scoped override must not become permanent
        _bulk_size = prev
