"""Engine control surface.

The reference exposes a handful of engine controls to Python
(``MXNDArrayWaitAll``, ``MXEngineSetBulkSize``, engine type selection via
``MXNET_ENGINE_TYPE`` — ``src/engine/engine.cc:32-48``).  On TPU the
scheduler *is* XLA+PJRT async dispatch, so these become thin shims with the
same observable semantics: ``wait_all`` blocks until every outstanding device
computation is done; ``naive_mode`` forces synchronous execution after every
op (the debugging escape hatch the NaiveEngine provides in the reference).
"""

from __future__ import annotations

import contextlib

import jax

_naive = False


def wait_all():
    """Block until all async device work has completed
    (reference: Engine::WaitForAll / MXNDArrayWaitAll)."""
    try:
        jax.effects_barrier()
    except Exception:
        jax.block_until_ready(jax.numpy.zeros(()))


def is_naive():
    return _naive


def set_naive(flag):
    """Enable synchronous (NaiveEngine-style) execution for debugging."""
    global _naive
    _naive = bool(flag)


@contextlib.contextmanager
def naive_mode():
    prev = _naive
    set_naive(True)
    try:
        yield
    finally:
        set_naive(prev)
