"""Runtime services: PRNG key stream, feature flags, engine shims.

The reference's per-device resource manager (``src/resource.cc``) hands ops
temp space and parallel PRNG states; on TPU the PRNG is functional, so the
"resource" becomes a key-splitting stream (``rng.py``).  The dependency
engine's user-facing control surface (``WaitForAll``, naive/bulk toggles,
``src/engine/engine.cc``) is shimmed in ``engine.py`` on top of JAX's async
dispatch.
"""

from . import rng, engine  # noqa: F401
