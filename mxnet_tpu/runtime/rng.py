"""Global PRNG key stream.

Replaces the reference's per-device random resources
(``include/mxnet/resource.h:104`` kParallelRandom, ``mx.random.seed``):
a process-global key that is split once per random op invocation.  Eager
random ops draw from this stream; traced programs (executor / hybridized
blocks) receive an explicit key input instead, so compiled graphs stay pure.
"""

from __future__ import annotations

import threading

import jax

_state = threading.local()
_DEFAULT_SEED = 0


def _ensure():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(_DEFAULT_SEED)


def seed(seed_value):
    """Seed the global generator (reference: mx.random.seed)."""
    _state.key = jax.random.PRNGKey(int(seed_value))


def next_key():
    """Split one fresh key off the global stream."""
    _ensure()
    _state.key, sub = jax.random.split(_state.key)
    return sub


def next_keys(n):
    _ensure()
    keys = jax.random.split(_state.key, n + 1)
    _state.key = keys[0]
    return keys[1:]
