"""Profiler — chrome://tracing output + aggregate op stats.

Reference capability: `src/profiler/profiler.h:87-108,256` (chrome-trace
JSON writer, mode bitmask, per-op stats) with the Python surface
`python/mxnet/profiler.py:33-151` (set_config/set_state/dump/dumps +
scriptable Task/Frame/Event/Counter/Marker objects).

TPU-native design: host-side spans are collected in-process (op dispatch
in `ops/registry.invoke`, executor forward/backward, API scopes); when
profiling is on, op calls block on their results so spans measure real
execution, not async dispatch (the reference's engine profiles the
worker thread for the same reason).  Device-side timelines come from the
XLA profiler: ``set_config(profile_device=True)`` starts a
``jax.profiler`` trace whose TensorBoard-loadable output lands next to
the chrome-trace file.
"""

from __future__ import annotations

import json
import os
import threading
import time

from . import sanitizer as _san
from .observability import metrics as _metrics

__all__ = ["set_config", "set_state", "pause", "resume", "dump", "dumps",
           "profiler_set_config", "profiler_set_state",
           "Domain", "Task", "Frame", "Event", "Counter", "Marker",
           "scope", "bump_counter", "counter_value", "counters",
           "reset_counters"]

_lock = _san.rlock(label="profiler._lock")
_events = []            # chrome trace event dicts
_agg = {}               # name -> [count, total_us, min_us, max_us]

# -- dispatch / compile counters --------------------------------------------
# Always-on (unlike spans, which need set_state('run')): these are the
# observable for the fused-train-step contract — "after warmup, one
# training step is exactly ONE jitted dispatch and ZERO compiles" —
# and tests must be able to assert it without turning tracing on.
# Sites:  eager_dispatches       ops/registry.invoke (per eager op)
#         executor_dispatches    LOGICAL executor-level calls
#                                (forward/train_step); a group2ctx
#                                segment-chained step counts ONCE even
#                                though it issues one program per
#                                segment — the counter's contract is
#                                the fused-step assertion, which never
#                                applies to grouped executors
#         fused_step_dispatches  Module full-fused step invocations
#         fused_step_compiles    fused-step trace-time (bumped inside the
#                                traced body, so cached executions add 0)
#         tree_apply_dispatches  Module partial-fused (multi-device)
#                                tree-update invocations
#         tree_apply_compiles    tree-update trace-time
#         parallel_step_dispatches / parallel_step_compiles
#                                ParallelTrainer fit_batch step
#
# Historically these lived in a private lock-free dict here; they are
# now Counter instruments in observability.metrics.REGISTRY (one
# uncontended per-counter lock — built from the sanitizer factories,
# so graftsan audits it — instead of the contended profiler RLock this
# comment used to justify avoiding), and this module keeps the
# original bump/value/snapshot surface as the compatibility layer.
# The same numbers the fused-step tests assert are what a scraper
# reads from metrics.exposition().

#: names bumped through this layer (so counters()/reset_counters keep
#: their historical "only the dispatch counters" scope even though the
#: registry also holds latency histograms and subsystem instruments)
_count_names = set()
_instruments = {}           # name -> Counter (lookup-free hot path)


def bump_counter(name, n=1):
    """Increment a named dispatch/compile counter (registry-backed)."""
    inst = _instruments.get(name)
    if inst is None:
        inst = _instruments[name] = _metrics.counter(
            name, "profiler dispatch/compile counter")
        _count_names.add(name)
    inst.inc(n)


def counter_value(name):
    inst = _instruments.get(name)
    return inst.value if inst is not None else 0


def counters():
    """Snapshot of all dispatch/compile counters."""
    return {name: _instruments[name].value
            for name in list(_count_names)}


def reset_counters():
    for name in list(_count_names):
        _instruments[name]._reset()
_config = {
    "filename": "profile.json",
    "profile_all": False,
    "profile_symbolic": True,
    "profile_imperative": True,
    "profile_api": False,
    "profile_memory": False,
    "profile_device": False,
    "aggregate_stats": False,
}
_state = {"running": False, "paused": False, "jax_trace": None}


def is_running():
    return _state["running"] and not _state["paused"]


# rank-0 worker can drive the profiler running inside kvstore SERVER
# processes (reference: include/mxnet/kvstore.h:43-56 profiler commands,
# python/mxnet/profiler.py profile_process='server',
# tests/nightly/test_server_profiling.py)
_kvstore_handle = None


def set_kvstore_handle(kv):
    """Register the dist kvstore used to route 'server' profiler
    commands (reference: profiler.py set_kvstore_handle)."""
    global _kvstore_handle
    _kvstore_handle = kv


def _to_server(head, body):
    if _kvstore_handle is None:
        raise ValueError(
            "profile_process='server' needs a dist kvstore (create one "
            "first; it registers itself)")
    _kvstore_handle._send_command_to_servers(head, body)


def _check_process(profile_process):
    if profile_process not in ("worker", "server"):
        raise ValueError("profile_process must be 'worker' or 'server', "
                         "got %r" % (profile_process,))
    return profile_process == "server"


def set_config(profile_process="worker", **kwargs):
    """Configure (reference: profiler.py set_config:33).  Accepts the
    reference's kwargs; unknown keys are rejected.
    ``profile_process='server'`` configures the profiler inside every
    kvstore server process instead."""
    if _check_process(profile_process):
        _to_server("profiler:set_config", kwargs)
        return
    for k, v in kwargs.items():
        if k not in _config:
            raise ValueError("unknown profiler option %r (known: %s)"
                             % (k, sorted(_config)))
        _config[k] = v


def set_state(state="stop", profile_process="worker"):
    """'run' starts collection, 'stop' ends it
    (reference: profiler.py set_state:89)."""
    if state not in ("run", "stop"):
        raise ValueError("state must be 'run' or 'stop'")
    if _check_process(profile_process):
        _to_server("profiler:set_state", state)
        return
    if state == "run" and not _state["running"]:
        _state["running"] = True
        _state["paused"] = False
        if _config["profile_device"]:
            import jax
            trace_dir = os.path.splitext(_config["filename"])[0] + \
                "_device"
            try:
                jax.profiler.start_trace(trace_dir)
                _state["jax_trace"] = trace_dir
            except Exception:
                _state["jax_trace"] = None
    elif state == "stop" and _state["running"]:
        _state["running"] = False
        if _state["jax_trace"]:
            import jax
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            _state["jax_trace"] = None


def pause():
    _state["paused"] = True


def resume():
    _state["paused"] = False


def record_span(name, cat, t0_s, t1_s, tid=0, args=None):
    """Add one complete ('X') event; timestamps in seconds."""
    if not is_running():
        return
    dur_us = (t1_s - t0_s) * 1e6
    with _lock:
        _events.append({
            "name": name, "cat": cat, "ph": "X",
            "ts": t0_s * 1e6, "dur": dur_us,
            "pid": os.getpid(), "tid": tid,
            **({"args": args} if args else {})})
        st = _agg.setdefault(name, [0, 0.0, float("inf"), 0.0])
        st[0] += 1
        st[1] += dur_us
        st[2] = min(st[2], dur_us)
        st[3] = max(st[3], dur_us)


def record_counter(name, value):
    # perf_counter, NOT time.time(): spans are stamped on the
    # monotonic base (record_span t0/t1 come from perf_counter), and a
    # trace mixing clock bases scatters counters decades away from the
    # spans in Perfetto
    if not is_running():
        return
    with _lock:
        _events.append({"name": name, "ph": "C",
                        "ts": time.perf_counter() * 1e6,
                        "pid": os.getpid(), "tid": 0,
                        "args": {name: value}})


def record_marker(name, cat="marker"):
    if not is_running():
        return
    with _lock:
        _events.append({"name": name, "cat": cat, "ph": "i",
                        "ts": time.perf_counter() * 1e6,
                        "pid": os.getpid(), "tid": 0, "s": "p"})


def dump(finished=True, profile_process="worker"):
    """Write the chrome-trace JSON (reference: profiler.py dump:122);
    load it at chrome://tracing or ui.perfetto.dev."""
    if _check_process(profile_process):
        _to_server("profiler:dump", bool(finished))
        return None
    if finished:
        set_state("stop")
    # flush the metrics-registry instruments as chrome-trace Counter
    # ('C') events at dump time, so ONE trace file carries both the
    # spans and the final instrument values (histograms flatten to
    # their count/sum pair — enough to spot "4000 host transfers
    # inside this window" next to the spans that caused them).
    # perf_counter base to land ON the spans' timeline (see
    # record_counter)
    now_us = time.perf_counter() * 1e6
    pid = os.getpid()
    counter_events = []
    for name, snap in _metrics.snapshot().items():
        if snap["kind"] == "histogram":
            args = {"count": snap["count"], "sum": snap["sum"]}
        else:
            args = {name: snap["value"]}
        counter_events.append({"name": "metrics/" + name, "ph": "C",
                               "ts": now_us, "pid": pid, "tid": 0,
                               "args": args})
    with _lock:
        data = {"traceEvents": list(_events) + counter_events,
                "displayTimeUnit": "ms"}
        with open(_config["filename"], "w") as f:
            json.dump(data, f)
    return _config["filename"]


def dumps(reset=False):
    """Aggregate per-op stats table (reference: aggregate_stats.cc /
    profiler.dumps)."""
    with _lock:
        lines = ["%-40s %8s %12s %12s %12s %12s" % (
            "Name", "Calls", "Total(us)", "Avg(us)", "Min(us)",
            "Max(us)")]
        for name, (cnt, tot, mn, mx) in sorted(
                _agg.items(), key=lambda kv: -kv[1][1]):
            lines.append("%-40s %8d %12.1f %12.1f %12.1f %12.1f" % (
                name[:40], cnt, tot, tot / max(cnt, 1), mn, mx))
        if reset:
            _agg.clear()
    return "\n".join(lines)


def reset():
    with _lock:
        _events.clear()
        _agg.clear()


# reference aliases
profiler_set_config = set_config
profiler_set_state = set_state


class scope:
    """Context manager timing a named host-side span."""

    def __init__(self, name, cat="user"):
        self.name = name
        self.cat = cat

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        record_span(self.name, self.cat, self._t0, time.perf_counter())


class Domain:
    """Grouping namespace for user objects (reference: Domain)."""

    def __init__(self, name):
        self.name = name

    def __repr__(self):
        return "Domain(%s)" % self.name


class _Span:
    def __init__(self, name, domain=None):
        self.name = name if domain is None else \
            "%s::%s" % (domain.name, name)
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        if self._t0 is not None:
            record_span(self.name, self._cat, self._t0,
                        time.perf_counter())
            self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class Task(_Span):
    _cat = "task"


class Frame(_Span):
    _cat = "frame"


class Event(_Span):
    _cat = "event"


class Marker:
    def __init__(self, name, domain=None):
        self.name = name if domain is None else \
            "%s::%s" % (domain.name, name)

    def mark(self, scope="process"):
        record_marker(self.name)


class Counter:
    """User counter (reference: ProfileCounter)."""

    def __init__(self, name, domain=None, value=0):
        self.name = name if domain is None else \
            "%s::%s" % (domain.name, name)
        self._value = value
        record_counter(self.name, value)

    def set_value(self, value):
        self._value = value
        record_counter(self.name, value)

    def increment(self, delta=1):
        self.set_value(self._value + delta)

    def decrement(self, delta=1):
        self.set_value(self._value - delta)

    def __iadd__(self, delta):
        self.increment(delta)
        return self

    def __isub__(self, delta):
        self.decrement(delta)
        return self
