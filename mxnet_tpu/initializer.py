"""Weight initializers (reference: python/mxnet/initializer.py, 738 LoC:
Uniform/Normal/Orthogonal/Xavier/MSRAPrelu/Bilinear/One/Zero/Constant...).

Initializers fill NDArrays in place (rebind) using the functional PRNG
stream; name-pattern dispatch (``_bias`` -> zeros etc.) mirrors
``Initializer.__call__``'s InitDesc routing in the reference.
"""

from __future__ import annotations

import math
import re

import numpy as _np

from .base import registry as _registry
from . import ndarray as nd

_reg = _registry("initializer")

__all__ = ["Initializer", "Uniform", "Normal", "Zero", "One", "Constant",
           "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias",
           "Mixed", "InitDesc", "register", "create"]


register = _reg.register


class InitDesc(str):
    """Name + attrs descriptor (reference: initializer.py InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    """Base initializer with name-based dispatch."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(desc)
        init = desc.attrs.get("__init__", "")
        if init:
            create(init)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var") or name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    def _init_weight(self, desc, arr):
        raise NotImplementedError

    def _init_bias(self, desc, arr):
        arr[:] = 0.0

    def _init_gamma(self, desc, arr):
        arr[:] = 1.0

    def _init_beta(self, desc, arr):
        arr[:] = 0.0

    def _init_zero(self, desc, arr):
        arr[:] = 0.0

    def _init_one(self, desc, arr):
        arr[:] = 1.0

    def _init_default(self, desc, arr):
        self._init_weight(desc, arr)

    def __repr__(self):
        return "%s(%s)" % (type(self).__name__, self._kwargs)

    def dumps(self):
        import json
        return json.dumps([type(self).__name__.lower(), self._kwargs])


@register
class Zero(Initializer):
    def _init_weight(self, desc, arr):
        arr[:] = 0.0


_reg.register(Zero, name="zeros")


@register
class One(Initializer):
    def _init_weight(self, desc, arr):
        arr[:] = 1.0


_reg.register(One, name="ones")


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, desc, arr):
        arr[:] = self.value


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, desc, arr):
        nd.random.uniform(-self.scale, self.scale, shape=arr.shape,
                          out=arr, dtype="float32")


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, desc, arr):
        nd.random.normal(0, self.sigma, shape=arr.shape, out=arr,
                         dtype="float32")


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, desc, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _np.random.normal(0.0, 1.0, (nout, nin))
        u, _v, q = _np.linalg.svd(tmp, full_matrices=False)
        res = u if u.shape == tmp.shape else q
        arr[:] = (self.scale * res).reshape(arr.shape).astype(_np.float32)


@register
class Xavier(Initializer):
    """Xavier/Glorot (reference: initializer.py Xavier — default for Gluon
    conv/dense weights)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, desc, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError("Xavier requires >=2D weight for %s" % desc)
        if len(shape) > 2:
            hw_scale = float(_np.prod(shape[2:]))
        fan_in = shape[1] * hw_scale
        fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("invalid factor_type %r" % self.factor_type)
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            nd.random.uniform(-scale, scale, shape=shape, out=arr)
        elif self.rnd_type == "gaussian":
            nd.random.normal(0, scale, shape=shape, out=arr)
        else:
            raise ValueError("invalid rnd_type %r" % self.rnd_type)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, desc, arr):
        weight = _np.zeros(arr.shape, dtype=_np.float32)
        shape = arr.shape
        f = shape[3] / 2.0
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (reference: initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        arr[:] = 0.0
        num_hidden = arr.shape[0] // 4
        a = arr.asnumpy()
        a[num_hidden:2 * num_hidden] = self.forget_bias
        arr[:] = a

    _init_bias = _init_weight


@register
class Mixed:
    """Pattern-routed initializer (reference: initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(str(name)):
                init(name, arr)
                return
        raise ValueError("no initializer pattern matches %r" % str(name))


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    if isinstance(name, str) and name.startswith("["):
        import json
        kind, kw = json.loads(name)
        return _reg.get(kind)(**kw)
    return _reg.get(name)(**kwargs)


# `mx.init` alias namespace (reference exposes mxnet.init = initializer)
import sys as _sys
init = _sys.modules[__name__]
