"""mx.random (reference: python/mxnet/random.py)."""

from __future__ import annotations

from .runtime import rng as _rng
from .ndarray import random as _ndrandom

uniform = _ndrandom.uniform
normal = _ndrandom.normal
randn = _ndrandom.randn
gamma = _ndrandom.gamma
exponential = _ndrandom.exponential
poisson = _ndrandom.poisson
negative_binomial = _ndrandom.negative_binomial
generalized_negative_binomial = _ndrandom.generalized_negative_binomial
multinomial = _ndrandom.multinomial
shuffle = _ndrandom.shuffle
randint = _ndrandom.randint


def seed(seed_state, ctx="all"):
    """Seed the global functional PRNG stream (reference: mx.random.seed)."""
    _rng.seed(seed_state)
