"""Monitor — per-op output inspection during training.

Reference capability: `python/mxnet/monitor.py:33` (Monitor installs an
executor callback via MXExecutorSetMonitorCallback; tic/toc collect
(step, op_name, stat) tuples each interval and toc_print logs them).
"""

from __future__ import annotations

import logging
import re

from .ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    """Collect statistics of every op's outputs each *interval* batches.

    Parameters
    ----------
    interval : int — batches between collections
    stat_func : NDArray -> NDArray/scalar (default: mean(abs(x)))
    pattern : regex on tap names
    sort : sort output by name
    monitor_all : tap interior ops too, not just graph outputs
    """

    def __init__(self, interval, stat_func=None, pattern=".*",
                 sort=False, monitor_all=True):
        if stat_func is None:
            def stat_func(x):
                return x.abs().mean() if hasattr(x, "abs") else x
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort
        self.monitor_all = monitor_all

        def stat_helper(name, arr):
            if not self.activated or not self.re_prog.match(name):
                return
            self.queue.append((self.step, name, self.stat_func(arr)))

        self.stat_helper = stat_helper

    def install(self, exe):
        """Attach to an executor (reference: monitor.py install)."""
        exe.set_monitor_callback(self.stat_helper,
                                 monitor_all=self.monitor_all)
        self.exes.append(exe)

    def tic(self):
        """Start collecting for this batch if the interval hits."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Stop collecting; return [(step, name, stat_string)]."""
        if not self.activated:
            return []
        self.activated = False
        res = []
        for exe in self.exes:
            for arr in exe.arg_dict.values():
                if isinstance(arr, NDArray):
                    arr.wait_to_read()
        for step, name, stat in self.queue:
            if isinstance(stat, NDArray):
                stat = stat.asnumpy()
            res.append((step, name, str(stat)))
        if self.sort:
            res.sort(key=lambda x: x[1])
        self.queue = []
        return res

    def toc_print(self):
        for step, name, stat in self.toc():
            logging.info("Batch: %7d %30s %s", step, name, stat)
