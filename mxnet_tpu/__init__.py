"""mxnet_tpu — a TPU-native deep learning framework with the capability
surface of Apache MXNet 1.3.1 (reference mounted at /root/reference).

Compute lowers to XLA (jit-cached eager ops, whole-graph compiled
executors); data parallelism is in-graph collectives over a device mesh;
irregular kernels are Pallas.  See SURVEY.md for the full blueprint.
"""

__version__ = "0.1.0"

import os as _os

# Server-role bootstrap: a process launched with DMLC_ROLE=server never
# returns to user code — the reference's behavior
# (python/mxnet/kvstore_server.py _init_kvstore_server_module, invoked
# from python/mxnet/__init__.py).  Implementation detail: we re-exec a
# fresh interpreter running ``-m mxnet_tpu.kvstore_server`` instead of
# blocking here, because a server loop inside this (still-initializing)
# package import would deadlock its handler threads on the package
# import lock the moment they unpickle an optimizer.
#
# This block sits at the TOP of the package, before any heavy imports:
# the pre-exec interpreter used to pay the FULL package import (jax,
# gluon, module, ...) only to throw it away in execv and import it all
# again — doubling server spin-up, which the multi-process dist drills
# pay per spawned server.
if _os.environ.get("DMLC_ROLE") == "server" and \
        not _os.environ.get("_MXTPU_SERVER_BOOT"):
    import sys as _sys
    # A ``python -m mxnet_tpu.kvstore_server ...`` launch imports this
    # package while argv[0] is still the "-m" placeholder; let it
    # proceed so its own argv (kv type) is honored rather than
    # re-execing over it.
    if _sys.argv and _sys.argv[0] != "-m":
        _os.environ["_MXTPU_SERVER_BOOT"] = "1"
        _pkg_parent = _os.path.dirname(_os.path.dirname(
            _os.path.abspath(__file__)))
        _pp = _os.environ.get("PYTHONPATH", "")
        _os.environ["PYTHONPATH"] = _pkg_parent + (_os.pathsep + _pp
                                                   if _pp else "")
        _os.execv(_sys.executable,
                  [_sys.executable, "-m", "mxnet_tpu.kvstore_server",
                   _os.environ.get("MXNET_KVSTORE_TYPE", "dist_sync")])

# Honor JAX_PLATFORMS before any backend init: this image's TPU-plugin
# site hook force-sets jax_platforms='axon,cpu' at interpreter startup,
# overriding even an explicit JAX_PLATFORMS=cpu env — so a CPU-only run
# would still dial (and possibly hang on) TPU device discovery.  The
# user's env var is authoritative here.
if _os.environ.get("JAX_PLATFORMS"):
    import jax as _jax
    try:
        if _jax.config.jax_platforms != _os.environ["JAX_PLATFORMS"]:
            _jax.config.update("jax_platforms",
                               _os.environ["JAX_PLATFORMS"])
    except Exception:
        pass

from .base import MXNetError  # noqa: F401
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, \
    num_tpus  # noqa: F401
from . import ndarray  # noqa: F401
from . import ndarray as nd  # noqa: F401
from . import symbol  # noqa: F401
from .symbol import AttrScope  # noqa: F401
from . import attribute  # noqa: F401
from . import name  # noqa: F401
from . import log  # noqa: F401
from . import symbol as sym  # noqa: F401
from .executor import Executor  # noqa: F401
from . import random  # noqa: F401
from . import autograd  # noqa: F401
from . import initializer  # noqa: F401
from .initializer import init  # noqa: F401
from . import optimizer  # noqa: F401
from . import lr_scheduler  # noqa: F401
from . import metric  # noqa: F401
from . import gluon  # noqa: F401
from . import io  # noqa: F401
from . import image  # noqa: F401
from . import module  # noqa: F401
from . import module as mod  # noqa: F401  (reference alias: mx.mod)
from . import model  # noqa: F401
from . import callback  # noqa: F401
from . import profiler  # noqa: F401
from . import monitor  # noqa: F401
from . import operator  # noqa: F401
from .monitor import Monitor  # noqa: F401
from .module import Module  # noqa: F401
from . import kvstore  # noqa: F401
from . import kvstore as kv  # noqa: F401
from . import rnn  # noqa: F401
from . import contrib  # noqa: F401
from . import parallel  # noqa: F401
from . import recordio  # noqa: F401
from . import visualization  # noqa: F401
viz = visualization  # reference alias: mx.viz
from . import subgraph  # noqa: F401
from . import resilience  # noqa: F401
from . import config  # noqa: F401
from . import sanitizer  # noqa: F401  (graftsan bridge — see MXNET_SAN)
from . import serve  # noqa: F401  (compiled inference subsystem)
from . import quantize  # noqa: F401  (serving-path int8 pipeline)
from . import rtc  # noqa: F401
from .runtime import engine  # noqa: F401

# Persistent XLA compilation cache (MXNET_COMPILE_CACHE_DIR): applied
# at import so EVERY compile in the process — fused train steps, AOT
# serve buckets, dist-drill child processes — can hit the on-disk
# cache.  No-op when the knob is unset; does not initialize a backend.
config.enable_compile_cache()


def waitall():
    engine.wait_all()
