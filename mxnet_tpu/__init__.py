"""mxnet_tpu — a TPU-native deep learning framework with the capability
surface of Apache MXNet 1.3.1 (reference mounted at /root/reference).

Compute lowers to XLA (jit-cached eager ops, whole-graph compiled
executors); data parallelism is in-graph collectives over a device mesh;
irregular kernels are Pallas.  See SURVEY.md for the full blueprint.
"""

__version__ = "0.1.0"

from .base import MXNetError  # noqa: F401
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, \
    num_tpus  # noqa: F401
from . import ndarray  # noqa: F401
from . import ndarray as nd  # noqa: F401
from . import symbol  # noqa: F401
from .symbol import AttrScope  # noqa: F401
from . import symbol as sym  # noqa: F401
from .executor import Executor  # noqa: F401
from . import random  # noqa: F401
from . import autograd  # noqa: F401
from . import initializer  # noqa: F401
from .initializer import init  # noqa: F401
from . import optimizer  # noqa: F401
from . import lr_scheduler  # noqa: F401
from . import metric  # noqa: F401
from . import gluon  # noqa: F401
from . import io  # noqa: F401
from . import image  # noqa: F401
from . import module  # noqa: F401
from . import module as mod  # noqa: F401  (reference alias: mx.mod)
from . import model  # noqa: F401
from . import callback  # noqa: F401
from . import profiler  # noqa: F401
from . import monitor  # noqa: F401
from . import operator  # noqa: F401
from .monitor import Monitor  # noqa: F401
from .module import Module  # noqa: F401
from . import kvstore  # noqa: F401
from . import kvstore as kv  # noqa: F401
from . import parallel  # noqa: F401
from . import recordio  # noqa: F401
from .runtime import engine  # noqa: F401


def waitall():
    engine.wait_all()
