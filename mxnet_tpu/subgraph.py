"""Subgraph framework — pluggable graph partitioning (reference:
src/operator/subgraph/subgraph_property.h:93 SubgraphProperty +
node-selector contract, registry :155, PartitionGraph pass
partition_graph.cc:738,766, selected by env MXNET_SUBGRAPH_BACKEND).

A ``SubgraphProperty`` supplies a ``SubgraphSelector`` that marks nodes
for grouping; ``partition_graph`` grows convex components from selected
nodes (no external node ever sits on a path between two members — the
invariant the reference's pass enforces) and replaces each with one
``_subgraph_exec`` node carrying the sub-Symbol as a static attribute.
A backend property can rewrite the subgraph it captures before wrapping
(the INT8 rewrite in ``contrib.quantization`` is this idea specialised
to quantization); captured subgraphs execute as a single jitted unit.

Nodes whose inputs include auxiliary-state variables (BatchNorm moving
stats) are never absorbed: aux updates inside a swallowed subgraph would
be lost — the reference's backends are likewise inference-fusion
focused.
"""

from __future__ import annotations

import os

__all__ = ["SubgraphSelector", "SubgraphProperty",
           "register_subgraph_property", "get_subgraph_property",
           "list_subgraph_backends", "partition_graph"]


class SubgraphSelector(object):
    """Node-selection contract (reference: subgraph_property.h:40-90)."""

    def select(self, node):
        """Start/continue a subgraph at *node*?"""
        return False

    def select_input(self, node, input_node):
        """Grow from *node* to its producer *input_node*?"""
        return self.select(input_node)

    def select_output(self, node, output_node):
        """Grow from *node* to its consumer *output_node*?"""
        return self.select(output_node)


class SubgraphProperty(object):
    """Backend hook: a selector plus the replacement-node factory
    (reference: subgraph_property.h:93, CreateSubgraphNode:105)."""

    def create_subgraph_selector(self):
        return SubgraphSelector()

    def rewrite_subgraph(self, subgraph_sym, subgraph_id):
        """Hook: transform the captured sub-Symbol before wrapping
        (quantize it, fuse it, ...).  Default: unchanged."""
        return subgraph_sym

    def create_subgraph_node(self, subgraph_sym, input_entries,
                             subgraph_id):
        """Build the replacement node: one ``_subgraph_exec`` op
        executing *subgraph_sym* with *input_entries* bound to its
        placeholder variables by name."""
        from .symbol.symbol import Node
        sub = self.rewrite_subgraph(subgraph_sym, subgraph_id)
        from .ops import registry as _reg
        node = Node(_reg.get_op("_subgraph_exec"),
                    "subgraph%d" % subgraph_id,
                    params={"subgraph": sub,
                            "input_names": tuple(
                                nm for nm, _e in input_entries),
                            "n_outputs": len(sub._outputs)},
                    inputs=[entry for _nm, entry in input_entries])
        return node


_PROPERTIES = {}


def register_subgraph_property(name, prop):
    """Register a backend under *name* (reference:
    MXNET_REGISTER_SUBGRAPH_PROPERTY)."""
    _PROPERTIES[name] = prop
    return prop


def get_subgraph_property(name):
    prop = _PROPERTIES[name]
    return prop() if isinstance(prop, type) else prop


def list_subgraph_backends():
    return sorted(_PROPERTIES)


def partition_graph(symbol, prop_or_name=None):
    """Partition *symbol* through a SubgraphProperty; returns a new
    Symbol with matched convex components replaced by _subgraph_exec
    nodes (reference: partition_graph.cc:738 PartitionGraph)."""
    from .symbol.symbol import Node, Symbol

    if prop_or_name is None:
        from .config import get_env
        prop_or_name = get_env("MXNET_SUBGRAPH_BACKEND")
        if not prop_or_name:
            return symbol
    prop = (get_subgraph_property(prop_or_name)
            if isinstance(prop_or_name, str) else prop_or_name)
    selector = prop.create_subgraph_selector()

    topo = symbol._topo()
    aux_ids = symbol._aux_var_ids()

    # ---- grow convex components in topo order -------------------------
    comp_of = {}     # id(node) -> component index
    comps = []       # component index -> [member nodes, topo order]
    anc_comps = {}   # id(node) -> set of component indices among ancestors

    for node in topo:
        acc = set()
        for inp, _s in node.inputs:
            acc |= anc_comps.get(id(inp), set())
            if id(inp) in comp_of:
                acc.add(comp_of[id(inp)])
        if not node.is_var:
            touches_aux = any(id(inp) in aux_ids
                              for inp, _s in node.inputs)
            if not touches_aux and selector.select(node):
                joined = None
                for inp, _s in node.inputs:
                    ci = comp_of.get(id(inp))
                    if ci is None or \
                            not selector.select_output(inp, node) or \
                            not selector.select_input(node, inp):
                        continue
                    # convexity: every other input that transitively
                    # depends on ci must itself be inside ci
                    ok = all(
                        comp_of.get(id(other)) == ci or
                        ci not in anc_comps.get(id(other), ())
                        for other, _t in node.inputs)
                    if ok:
                        joined = ci
                        break
                if joined is None:
                    joined = len(comps)
                    comps.append([])
                comps[joined].append(node)
                comp_of[id(node)] = joined
        anc_comps[id(node)] = acc

    live = {ci for ci, c in enumerate(comps) if len(c) >= 2}
    if not live:
        return symbol
    member_of = {id(n): ci for ci, c in enumerate(comps)
                 for n in c if ci in live}

    # ---- usage map: which output entries are consumed where -----------
    users = {}       # id(node) -> [(consumer node, out_slot used)]
    for n in topo:
        for inp, slot in n.inputs:
            users.setdefault(id(inp), []).append((n, slot))
    head_set = {(id(n), s) for n, s in symbol._outputs}

    # ---- reconstruction: create replacement nodes with RAW (original)
    # input entries, then patch every created node's inputs through the
    # completed entry_map — a component finalized late in topo order can
    # feed one finalized early, so resolution must be deferred until the
    # map is complete (else the original producer leaks into the new
    # graph and runs twice).
    entry_map = {}   # (id(old node), slot) -> (new node, slot)
    created = []     # new nodes whose .inputs hold raw original entries
    remaining = {ci: len(comps[ci]) for ci in live}

    def finalize(ci):
        members = comps[ci]
        member_ids = {id(m) for m in members}
        # external inputs (order = first use), placeholder vars by name
        ext, var_map = [], {}
        for m in members:
            for inp, slot in m.inputs:
                if id(inp) in member_ids:
                    continue
                key = (id(inp), slot)
                if key in var_map:
                    continue
                pname = (inp.name if inp.is_var
                         else "__sg%d_in%d" % (ci, len(ext)))
                var_map[key] = Node(None, pname)
                ext.append((pname, (inp, slot)))
        # member output entries visible outside
        out_entries = []
        for m in members:
            slots = sorted({s for u, s in users.get(id(m), [])
                            if id(u) not in member_ids} |
                           {s for nid, s in head_set if nid == id(m)})
            out_entries.extend((m, s) for s in slots)
        # clone members into the sub-Symbol over placeholder vars
        clones = {}

        def clone(n):
            if id(n) in clones:
                return clones[id(n)]
            new_inputs = []
            for inp, slot in n.inputs:
                if id(inp) in member_ids:
                    new_inputs.append((clone(inp), slot))
                else:
                    new_inputs.append((var_map[(id(inp), slot)], 0))
            c = Node(n.op, n.name, dict(n.params), new_inputs,
                     dict(n.attrs))
            clones[id(n)] = c
            return c

        sub_sym = Symbol([(clone(n), s) for n, s in out_entries])
        sg_node = prop.create_subgraph_node(sub_sym, ext, ci)
        created.append(sg_node)
        for out_slot, (m, s) in enumerate(out_entries):
            entry_map[(id(m), s)] = (sg_node, out_slot)

    for node in topo:
        ci = member_of.get(id(node))
        if ci is not None:
            remaining[ci] -= 1
            if remaining[ci] == 0:
                finalize(ci)
            continue
        if node.is_var:
            continue
        # clone iff any input was (or will be) remapped — members of
        # not-yet-finalized components count
        if not any((id(inp), slot) in entry_map or id(inp) in member_of
                   for inp, slot in node.inputs):
            continue
        clone = Node(node.op, node.name, dict(node.params),
                     list(node.inputs), dict(node.attrs))
        created.append(clone)
        for s in range(node.num_outputs()):
            entry_map[(id(node), s)] = (clone, s)

    # ---- deferred patch: resolve raw entries through the full map -----
    for n in created:
        n.inputs = [entry_map.get((id(src), s), (src, s))
                    for src, s in n.inputs]
    new_heads = [entry_map.get((id(n), s), (n, s))
                 for n, s in symbol._outputs]
    return Symbol(new_heads)


# --- built-in demonstration backend ---------------------------------------

_ELEMWISE = {"Activation", "relu", "sigmoid", "tanh", "exp", "log",
             "negative", "sqrt", "square", "clip",
             "broadcast_add", "broadcast_sub", "broadcast_mul",
             "broadcast_div", "elemwise_add", "elemwise_sub",
             "elemwise_mul", "elemwise_div", "_plus_scalar",
             "_minus_scalar", "_mul_scalar", "_div_scalar"}


class _ElemwiseFuseSelector(SubgraphSelector):
    def select(self, node):
        return (not node.is_var) and node.op.name in _ELEMWISE


class ElemwiseFuseProperty(SubgraphProperty):
    """Groups contiguous elementwise chains into one compiled unit —
    the structural demo backend (XLA fuses the math either way; the
    group executes as a single _subgraph_exec program)."""

    def create_subgraph_selector(self):
        return _ElemwiseFuseSelector()


register_subgraph_property("MXTPU_FUSE", ElemwiseFuseProperty)
