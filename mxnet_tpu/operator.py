"""Custom-op bridge — user Python operators inside compiled graphs.

Reference capability: `python/mxnet/operator.py` (1,101 LoC: CustomOp /
CustomOpProp / register + callback trampolines into
`src/operator/custom/custom-inl.h`, which runs user Python on a
dedicated worker thread so the engine never blocks on the GIL).

TPU-native design: the user's `forward`/`backward` run on host via
`jax.pure_callback`, which XLA schedules like any other op — the
device-side program stalls only at the data dependency, the reference's
dedicated-thread behavior falling out of XLA's async host callbacks.
Gradients wire through `jax.custom_vjp`, so Custom ops compose with
autograd, the whole-graph executor, and hybridize.
"""

from __future__ import annotations

import functools

import numpy as _np
import jax
import jax.numpy as jnp

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop"]

_REGISTRY = {}


class CustomOp:
    """Base class for user operators (reference: operator.py CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write *src* into *dst* honoring the grad request
        (reference: CustomOp.assign)."""
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src


class CustomOpProp:
    """Operator properties: arity, shapes, types
    (reference: operator.py CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def need_top_grad(self):
        return self.need_top_grad_

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Class decorator registering a CustomOpProp subclass under
    *reg_name* (reference: operator.py register)."""
    def do_register(prop_cls):
        _REGISTRY[reg_name] = prop_cls
        return prop_cls
    return do_register


def get_prop(op_type):
    if op_type not in _REGISTRY:
        raise MXNetError(
            "custom op %r is not registered (use "
            "@mxnet_tpu.operator.register(%r) on a CustomOpProp)"
            % (op_type, op_type))
    return _REGISTRY[op_type]


def _np_wrap(arrs):
    """Wrap numpy arrays as NDArrays for the user callback."""
    from .ndarray import NDArray
    return [NDArray(jnp.asarray(a)) for a in arrs]


@functools.lru_cache(maxsize=None)
def _build_custom(op_type, frozen_kwargs, in_shapes, in_dtypes):
    """Compile-cached custom-vjp callable for one (op, signature)."""
    kwargs = dict(frozen_kwargs)
    prop = get_prop(op_type)(**kwargs)
    n_out = len(prop.list_outputs())
    shapes_in = [tuple(s) for s in in_shapes]
    sh_in, sh_out, _ = prop.infer_shape([list(s) for s in shapes_in])
    ty_in, ty_out, _ = prop.infer_type(list(in_dtypes))
    out_spec = tuple(jax.ShapeDtypeStruct(tuple(s), t)
                     for s, t in zip(sh_out, ty_out))
    in_spec = tuple(jax.ShapeDtypeStruct(tuple(s), t)
                    for s, t in zip(sh_in, ty_in))
    op_inst = prop.create_operator(None, sh_in, ty_in)

    def fwd_cb(*ins):
        in_nd = _np_wrap(ins)
        out_nd = _np_wrap([_np.zeros(s, t)
                           for s, t in zip(sh_out, ty_out)])
        op_inst.forward(True, ["write"] * n_out, in_nd, out_nd, [])
        return tuple(o.asnumpy() for o in out_nd)

    def bwd_cb(*flat):
        n_in = len(in_spec)
        ins = flat[:n_in]
        outs = flat[n_in:n_in + n_out]
        cots = flat[n_in + n_out:]
        in_nd = _np_wrap(ins)
        out_nd = _np_wrap(outs)
        cot_nd = _np_wrap(cots)
        grad_nd = _np_wrap([_np.zeros(s, t)
                            for s, t in zip(sh_in, ty_in)])
        op_inst.backward(["write"] * len(in_spec), cot_nd, in_nd,
                         out_nd, grad_nd, [])
        return tuple(g.asnumpy() for g in grad_nd)

    @jax.custom_vjp
    def run(*ins):
        return jax.pure_callback(fwd_cb, out_spec, *ins)

    def run_fwd(*ins):
        outs = run(*ins)
        return outs, (ins, outs)

    def run_bwd(res, cots):
        ins, outs = res
        return jax.pure_callback(bwd_cb, in_spec, *ins, *outs, *cots)

    run.defvjp(run_fwd, run_bwd)
    return run


def invoke_custom(inputs, op_type, **kwargs):
    """Entry used by the registered 'Custom' op."""
    shapes = tuple(tuple(x.shape) for x in inputs)
    dtypes = tuple(_np.dtype(x.dtype) for x in inputs)
    frozen = tuple(sorted(kwargs.items()))
    fn = _build_custom(op_type, frozen, shapes, dtypes)
    return fn(*inputs)
