"""Environment-knob registry (reference: §5.6 config system —
~32 documented ``MXNET_*`` vars in docs/faq/env_var.md read through
``dmlc::GetEnv`` at singleton init).

One typed, documented registry instead of scattered ``os.environ`` reads:
every knob this framework consults is declared here with type, default,
and doc; ``describe()`` prints the env-var reference table the way
docs/faq/env_var.md documents the reference's.  Values are read at call
time (not import time) so tests can monkeypatch the environment.

Three layers resolve every read, in precedence order
(docs/autotuning.md):

1. **explicit env** — the variable is exported in ``os.environ``;
   an operator's export always wins,
2. **tuned override** — a value installed by :func:`tuned_override`
   (the autotuner's ``TuningStore`` applies winning configs here),
3. **registered default** — the ``register_env`` declaration.
"""

from __future__ import annotations

import os

__all__ = ["register_env", "get_env", "list_env", "describe",
           "tuned_override", "tuned_overrides", "clear_tuned",
           "resolve_env", "env_is_set", "enable_compile_cache"]

_REGISTRY = {}

# the tuned-override layer: knob name -> typed value.  Sits BETWEEN
# the environment and the registered default — get_env consults it
# only when the env var is not exported, so a tuned store can never
# shadow an operator's explicit setting.
_TUNED = {}


class _Knob(object):
    __slots__ = ("name", "type", "default", "doc")

    def __init__(self, name, typ, default, doc):
        self.name = name
        self.type = typ
        self.default = default
        self.doc = doc


def register_env(name, typ, default, doc):
    """Declare an environment knob (type in {int, float, str, bool})."""
    _REGISTRY[name] = _Knob(name, typ, default, doc)
    return _REGISTRY[name]


def _coerce(knob, value):
    if knob.type is bool and isinstance(value, str):
        return value.lower() not in ("0", "false", "off", "")
    try:
        return knob.type(value)
    except (TypeError, ValueError):
        raise ValueError("env %s=%r is not a valid %s"
                         % (knob.name, value, knob.type.__name__))


def get_env(name):
    """Read a registered knob: explicit env > tuned override >
    registered default (typed at every layer)."""
    return resolve_env(name)


def resolve_env(name, tuned=None):
    """Read a registered knob with an explicit per-call tuned value.

    Precedence: exported env var > *tuned* argument > the process-wide
    :func:`tuned_override` layer > registered default.  The *tuned*
    argument is how per-model tuning records (a registry consulting
    the ``TuningStore`` for one model) participate without mutating
    process-wide state; ``None`` means "no per-call tuning"."""
    knob = _REGISTRY[name]
    raw = os.environ.get(name)
    if raw is not None:
        return _coerce(knob, raw)
    if tuned is not None:
        return _coerce(knob, tuned)
    if name in _TUNED:
        return _TUNED[name]
    return knob.default


def env_is_set(name):
    """Is the knob's variable explicitly exported?  (The predicate a
    store-consulting call site uses to honor env-wins precedence.)"""
    return os.environ.get(name) is not None


def tuned_override(name, value):
    """Install a tuned value for a registered knob.  It applies to
    every subsequent :func:`get_env` read UNLESS the env var is
    exported — explicit env always wins (regression-tested in
    tests/test_autotune.py).  Returns the typed value installed."""
    knob = _REGISTRY[name]
    _TUNED[name] = _coerce(knob, value)
    return _TUNED[name]


def tuned_overrides():
    """The currently installed tuned layer (copy)."""
    return dict(_TUNED)


def clear_tuned(name=None):
    """Drop one tuned override (or all of them with no argument)."""
    if name is None:
        _TUNED.clear()
    else:
        _TUNED.pop(name, None)


def list_env():
    return sorted(_REGISTRY)


def describe():
    """The env-var reference table (reference: docs/faq/env_var.md)."""
    lines = []
    for name in list_env():
        k = _REGISTRY[name]
        lines.append("%-40s %-6s default=%-12r %s"
                     % (name, k.type.__name__, k.default, k.doc))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Knob declarations — every env var the framework consults.
# ---------------------------------------------------------------------------

register_env("MXNET_ENGINE_TYPE", str, "XLAAsync",
             "Engine selection; 'NaiveEngine' forces synchronous "
             "execution after every op (reference: engine.cc:32-48)")
register_env("MXNET_EXEC_BULK_EXEC_TRAIN", bool, True,
             "Compile the whole training graph as one XLA program; off "
             "= per-node execution for debugging/monitoring "
             "(reference: graph_executor.cc:1187 bulk segments)")
register_env("MXNET_EXEC_BULK_EXEC_INFERENCE", bool, True,
             "Same as MXNET_EXEC_BULK_EXEC_TRAIN for inference graphs")
register_env("MXNET_KVSTORE_SYNC_TIMEOUT", float, 120.0,
             "Seconds a dist_sync server waits for all workers' pushes "
             "or barrier arrivals before raising")
register_env("MXNET_KVSTORE_HEARTBEAT_INTERVAL", float, 1.0,
             "Seconds between worker heartbeats feeding dead-node "
             "detection (reference: ps-lite heartbeats)")
register_env("MXNET_KVSTORE_CONNECT_TIMEOUT", float, 120.0,
             "Seconds a dist worker retries connecting to its servers "
             "(fresh socket per attempt) before raising — covers "
             "server-process spin-up, which includes a full package "
             "import")
register_env("MXNET_KVSTORE_RPC_TIMEOUT", float, 150.0,
             "Per-call socket timeout (seconds) on dist bulk RPC "
             "sockets: a server that dies mid-reply surfaces as a "
             "typed RPCTimeoutError instead of hanging the worker "
             "forever in recv; must exceed MXNET_KVSTORE_SYNC_TIMEOUT "
             "(sync pushes block server-side until the round "
             "completes); 0 = no timeout (legacy hang behavior)")
register_env("MXNET_KVSTORE_RPC_RETRIES", int, 5,
             "Transport attempts per dist bulk RPC: a timed-out or "
             "connection-broken call reconnects and resends the SAME "
             "(rank, seq) request id with jittered backoff; the "
             "server dedup window makes retried mutations apply "
             "exactly once")
register_env("MXNET_KVSTORE_DEDUP_WINDOW", int, 256,
             "Per-rank server-side idempotency window: how many "
             "recent mutating request ids (push/init/barrier) the "
             "server remembers so a retried RPC is answered from "
             "cache instead of re-applied")
register_env("MXNET_KVSTORE_EVICT_TIMEOUT", float, 10.0,
             "Seconds without a heartbeat before a sync-mode server "
             "treats a missing contributor as provably dead on "
             "sync/barrier deadline expiry and evicts it (survivors "
             "make progress); an alive-but-slow laggard instead "
             "raises a loud SyncTimeoutError naming it")
register_env("MXNET_KVSTORE_SNAPSHOT_PREFIX", str, "",
             "Checkpoint prefix for periodic KVStore server state "
             "snapshots (store + optimizer state + dedup window via "
             "resilience.CheckpointManager); a restarted server "
             "restores the snapshot so worker rejoin pulls resume "
             "from committed state; empty = snapshots off; server s "
             "of a group appends '-s<id>'")
register_env("MXNET_KVSTORE_SNAPSHOT_EVERY", int, 1,
             "Applies between server state snapshots (counter-based, "
             "deterministic); only consulted when "
             "MXNET_KVSTORE_SNAPSHOT_PREFIX is set; 0 = never")
register_env("MXNET_KVSTORE_JOIN_TIMEOUT", float, 120.0,
             "Seconds a joining/rejoining worker's wait_admission() "
             "polls for its admission to the expected-contributor set "
             "(admission happens at sync-round boundaries, so a "
             "stalled job admits nobody) before raising")
register_env("MXNET_KVSTORE_ADMIT_POLL", float, 0.2,
             "Poll interval (seconds) of wait_admission() and the "
             "joiner-side job-metadata fetch during mid-epoch "
             "admission")
register_env("MXNET_SAN", str, "",
             "graftsan runtime sanitizer components to enable: comma "
             "list of race,recompile,donation,transfer, or 'all'; "
             "empty = off (zero overhead; see docs/sanitizers.md)")
register_env("MXNET_IR_AUDIT", str, "",
             "Audit every AOT program's lowered StableHLO with the "
             "graftir rules (tools/graftir) as it is built: findings "
             "are logged, counted and evented ('iraudit' category); "
             "empty = off (zero overhead; see docs/ir_audit.md)")
register_env("MXNET_OBS", str, "",
             "Structured run-event categories to record to "
             "events.jsonl: comma list of compile,guard,chaos,"
             "checkpoint,preempt,retry,respawn,warning,kvstore,"
             "membership,supervisor,watchdog,serve,decode,fleet,"
             "autotune,iraudit, or 'all'; "
             "empty = off (no file, zero per-event cost; see "
             "docs/observability.md)")
register_env("MXNET_OBS_PATH", str, "events.jsonl",
             "Path of the structured run-event log (created lazily on "
             "the first recorded event)")
register_env("MXNET_OBS_RATE", int, 200,
             "Max run events recorded per second; excess events are "
             "counted and surfaced as 'dropped' on the next admitted "
             "event (0 = uncapped)")
register_env("MXNET_KVSTORE_BIGARRAY_BOUND", int, 1000000,
             "Arrays above this many elements shard across all servers "
             "(reference: kvstore_dist.h:58)")
register_env("MXNET_KVSTORE_TYPE", str, "local",
             "Default kvstore type for examples/launchers")
register_env("MXNET_SUBGRAPH_BACKEND", str, "",
             "Subgraph property applied at bind time "
             "(reference: partition_graph.cc; see mxnet_tpu.subgraph)")
register_env("MXNET_TPU_MATMUL_PRECISION", str, "",
             "Override jax matmul precision: bfloat16 | float32 | "
             "tensorfloat32 (TPU-native knob)")
register_env("MXNET_MODULE_FUSED_STEP", bool, True,
             "Module.forward_backward_update fuses forward + backward + "
             "gradient reduction + optimizer update into one donated "
             "XLA program when eligible; off = always run the legacy "
             "per-parameter Updater loop (TPU-native knob)")
register_env("MXNET_GUARD_NONFINITE", bool, False,
             "Skip optimizer updates whose loss/gradients contain "
             "NaN/Inf: one in-graph isfinite reduction inside the "
             "fused train step selects the unchanged params/state, so "
             "a diverged step costs no extra dispatch and no "
             "recompile (TPU-native knob; see docs/resilience.md)")
register_env("MXNET_GUARD_READBACK_LAG", int, 0,
             "Async non-finite-guard accounting on the FULL-fused "
             "step: defer the guard counter's scalar device->host "
             "readback by up to this many steps, so the host "
             "dispatches step N+1 while the device still runs step N "
             "(params/opt-state/aux stay protected in-graph by the "
             "where-select regardless).  Deferred readbacks resolve "
             "FIFO, so max_consecutive divergence actions fire within "
             "this many steps of the real divergence; the backlog is "
             "drained at epoch end, on preemption, and whenever job "
             "state is captured.  0 = synchronous (legacy, one "
             "blocking readback per step); see "
             "docs/perf_input_pipeline.md")
register_env("MXNET_DEVICE_PREFETCH", int, 0,
             "Ring depth for the fit()-level DevicePrefetcher wrap: "
             "training loops wrap their data iterator so host decode "
             "AND jax.device_put run on a background thread into a "
             "ring of this many device-resident batches (device "
             "memory: depth x batch bytes); 0 = off; "
             "fit(device_prefetch=...) overrides in both directions "
             "(see docs/perf_input_pipeline.md)")
register_env("MXNET_GUARD_MAX_BAD_STEPS", int, 0,
             "With the non-finite guard on, this many CONSECUTIVE "
             "skipped steps trigger the divergence action (raise, or "
             "rollback via Module.set_nonfinite_guard); 0 = count "
             "and skip only")
register_env("MXNET_CHAOS", str, "",
             "Fault-injection spec for the resilience chaos harness, "
             "e.g. 'fail_file_writes=2,nan_grads_at_step=3'; 'on' "
             "enables the harness with nothing armed; empty = off "
             "(see mxnet_tpu/resilience/chaos.py)")
register_env("MXNET_CHECKPOINT_KEEP_LAST", int, 0,
             "Default keep-last-K rotation for CheckpointManager "
             "(older epochs' files are deleted once unreferenced); "
             "0 = keep every checkpoint")
register_env("MXNET_WATCHDOG_TIMEOUT", float, 300.0,
             "Seconds the supervisor's watchdog tolerates a stalled "
             "heartbeat (no batch-boundary tick) from a live child "
             "before declaring it HUNG — wedged collective, "
             "deadlocked dataloader — dumping a flight record, and "
             "killing/restarting it; measured on the monotonic clock")
register_env("MXNET_SUPERVISOR_RESTARTS", int, 3,
             "Restart budget of resilience.supervisor: how many child "
             "deaths + hang-kills are restarted (with jittered "
             "backoff) from the latest checkpoint before the "
             "supervisor gives up and surfaces the failure")
register_env("MXNET_HEARTBEAT_FILE", str, "",
             "Path of the supervised-job heartbeat file; set by the "
             "supervisor for its child — when present, fit()-style "
             "training loops tick it once per batch (empty = "
             "unsupervised, zero overhead)")
register_env("MXNET_FLIGHT_STACKS", str, "",
             "Path where a supervised child's faulthandler dumps "
             "all-thread stacks on SIGUSR1 (set by the supervisor; "
             "part of the hang flight record)")
register_env("MXNET_FLIGHT_SNAPSHOT", str, "",
             "Path where a supervised child writes a metrics "
             "snapshot on SIGUSR2 (best-effort: Python-level handler, "
             "so only sleep-style hangs can honor it)")
register_env("MXNET_OPTSTATE_MISMATCH", str, "raise",
             "What load_optimizer_states does when the blob was "
             "written by a different optimizer class or hyper-param "
             "signature: 'raise' (typed StateMismatchError) or "
             "'reinit' (warn and start from fresh optimizer state)")
register_env("MXNET_DATALOADER_RESPAWNS", int, 2,
             "How many crashed DataLoader worker processes are "
             "respawned (with backoff, lost batches resubmitted) "
             "before the loader gives up and raises")
register_env("MXNET_UPDATE_ON_KVSTORE", bool, True,
             "Run the optimizer on the kvstore server (dist) / store "
             "(local) instead of locally (reference: module/trainer)")
register_env("MXNET_CPU_WORKER_NTHREADS", int, 0,
             "Host-side worker threads for the data pipeline; 0 = "
             "library default (reference: "
             "threaded_engine_perdevice.cc:79)")
register_env("MXNET_USE_NATIVE_RECORDIO", bool, True,
             "Read .rec files through the native C++ reader "
             "(src/io/recordio_reader.cc) when built; off = pure Python")
register_env("MXNET_ENGINE_INFO", bool, False,
             "Verbose engine scheduling debug output "
             "(reference: threaded_engine.h:302)")
register_env("MXNET_COMPILE_CACHE_DIR", str, "",
             "Directory for jax's persistent XLA compilation cache "
             "(jax_compilation_cache_dir): cold starts — serving "
             "fleets, multi-process dist drills, supervisor restarts "
             "— reload compiled programs from disk instead of paying "
             "a full compile; empty = off (see docs/serving.md and "
             "docs/perf_fused_step.md)")
register_env("MXNET_COMPILE_CACHE_MIN_SECS", float, 0.0,
             "Minimum compile time (seconds) for a program to be "
             "written to the persistent compilation cache "
             "(jax_persistent_cache_min_compile_time_secs); 0 caches "
             "everything — serving ladders are many small programs")
register_env("MXNET_SERVE_MAX_WAIT_MS", float, 2.0,
             "How long the serve DynamicBatcher holds a non-full "
             "batch open for more arrivals, measured from the oldest "
             "queued request (milliseconds, monotonic clock); 0 = "
             "dispatch immediately, no coalescing window")
register_env("MXNET_SERVE_MAX_BATCH", int, 0,
             "Row cap per coalesced serve batch; 0 = the model's "
             "bucket-ladder top rung")
register_env("MXNET_SERVE_MAX_QUEUE", int, 1024,
             "Admission control: max requests waiting in one serve "
             "DynamicBatcher — submit past the cap raises a typed "
             "OverloadError (load shedding) instead of queueing "
             "unboundedly; 0 = unbounded (legacy)")
register_env("MXNET_SERVE_MAX_QUEUE_BYTES", int, 1 << 28,
             "Admission control: max payload bytes waiting in one "
             "serve DynamicBatcher (the byte-sided overload cap "
             "alongside MXNET_SERVE_MAX_QUEUE); 0 = unbounded")
register_env("MXNET_SERVE_DEFAULT_DEADLINE_MS", float, 0.0,
             "Default per-request serving deadline (milliseconds, "
             "monotonic clock) applied when submit() passes none: an "
             "expired request is shed BEFORE padding/dispatch and its "
             "future resolves with a typed DeadlineExceededError; "
             "0 = no deadline")
register_env("MXNET_SERVE_DISPATCHER_RESTARTS", int, 3,
             "How many serve dispatcher-thread crashes (an exception "
             "escaping the batching loop, not a per-batch dispatch "
             "failure) are restarted with jittered backoff before the "
             "batcher declares itself unhealthy and fails every "
             "queued future loudly")
register_env("MXNET_SERVE_DRAIN_TIMEOUT", float, 30.0,
             "Default bound (seconds) on graceful drain: how long "
             "Registry.drain / unload(drain=True) / an alias-cutover "
             "flush waits for accepted serve requests to finish "
             "before proceeding anyway")
register_env("MXNET_SERVE_KV_BLOCK_SIZE", int, 16,
             "Tokens per paged KV-cache block (serve.kvpool): the "
             "granularity decode sessions allocate cache memory at — "
             "smaller blocks waste less tail memory per session, "
             "larger blocks mean fewer scatter rows per tick")
register_env("MXNET_SERVE_KV_BLOCKS", int, 256,
             "Paged KV pool capacity in blocks (per decode engine, "
             "including the reserved null block): bounds TOTAL cache "
             "memory across every concurrent decode session; an "
             "admission that cannot get its blocks sheds with a "
             "typed KVPoolExhausted")
register_env("MXNET_SERVE_DECODE_MAX_WAIT_MS", float, 2.0,
             "How long an IDLE decode batcher holds its first tick "
             "open for more sessions to arrive (milliseconds, "
             "monotonic clock) so co-arriving sessions share one "
             "session-count rung from the start; once decoding, "
             "ticks run back-to-back and joins land between ticks")
register_env("MXNET_SERVE_HTTP_PORT", int, 0,
             "Per-replica HTTP probe port (serve.replica): serves "
             "/metrics (Prometheus exposition of the process metrics "
             "registry), /healthz (liveness) and /readyz (readiness "
             "+ per-model health JSON) over stdlib http.server so "
             "the fleet router and any external orchestrator can "
             "scrape it; 0 = probe server off (the fleet passes an "
             "explicit port when it spawns replicas)")
register_env("MXNET_SERVE_HEDGE_MS", float, 0.0,
             "Router-side request hedging: after this many "
             "milliseconds without an answer, re-issue the still-"
             "pending predict (SAME request id) to a second replica "
             "— first typed answer wins, the loser is cancelled "
             "through the replica's idempotency window so no request "
             "is ever dispatched twice on one replica or answered "
             "twice; 0 = hedging off")
register_env("MXNET_SERVE_RPC_TIMEOUT", float, 60.0,
             "Per-call socket timeout (seconds) on router->replica "
             "RPCs: a replica that dies mid-reply surfaces as a "
             "transport failure the router fails over, instead of "
             "hanging the caller; 0 = no timeout")
register_env("MXNET_SERVE_ROUTER_RETRIES", int, 3,
             "Total transport attempts per routed request (first "
             "try + failovers): a connection failure retries the "
             "SAME (client, seq, incarnation) request id on the "
             "next eligible replica — wrapping around to an "
             "already-tried replica only when no fresh one is left, "
             "where the dedup window answers a retried id from "
             "cache instead of re-dispatching")
register_env("MXNET_SERVE_BREAKER_FAILURES", int, 3,
             "Consecutive transport failures that open one "
             "replica's router-side circuit breaker (no requests "
             "routed while open)")
register_env("MXNET_SERVE_BREAKER_COOLDOWN", float, 1.0,
             "Seconds an open circuit breaker waits before letting "
             "ONE half-open trial request through; success closes "
             "the breaker, failure re-opens it for another cooldown")
register_env("MXNET_SERVE_FLEET_HEARTBEAT", float, 0.5,
             "Router health-probe cadence (seconds): each replica "
             "is probed with a HEALTH RPC this often, feeding "
             "readiness-aware routing and heartbeat-staleness "
             "ejection")
register_env("MXNET_SERVE_EJECT_TIMEOUT", float, 5.0,
             "Seconds without a successful health probe before the "
             "router ejects a replica from the rotation (breaker "
             "forced open); the next successful probe rejoins it")
register_env("MXNET_TUNING_STORE", str, "",
             "Path of the autotuner's JSON TuningStore "
             "(tools/autotune.py output).  When set, ModelRegistry."
             "load / DynamicBatcher / DecodeEngine consult it for the "
             "winning config keyed (model_name, device_kind, "
             "workload); an exported env var still beats a stored "
             "tuning (see docs/autotuning.md); empty = no store")
register_env("MXNET_SERVE_DEDUP_WINDOW", int, 256,
             "Per-client replica-side idempotency window: how many "
             "recent predict request ids each replica remembers so "
             "a retried or hedged RPC is answered from cache "
             "instead of re-dispatched (in-flight entries are "
             "never trimmed)")
register_env("MXNET_SERVE_DECODE_REBUILDS", int, 2,
             "How many decode tick-loop crashes a DecodeBatcher "
             "survives by quarantine-and-rebuild: the suspect KVPool "
             "is quarantined, a fresh same-shape pool is allocated "
             "against the already-warm tick/prefill programs (zero "
             "new compiles) and journaled sessions are re-admitted "
             "via re-prefill + replayed ticks; past the budget the "
             "batcher degrades to unhealthy typed-fail")


def enable_compile_cache():
    """Apply the ``MXNET_COMPILE_CACHE_DIR`` knob: point jax's
    persistent compilation cache at the directory (created if
    missing) so every process sharing it — a serving fleet, the
    multi-process dist drills, supervisor-restarted jobs — pays each
    distinct program's compile once, ever.  Returns True when the
    cache was enabled.  Called at package import; safe to call again
    after mutating the environment (tests)."""
    path = get_env("MXNET_COMPILE_CACHE_DIR")
    if not path:
        return False
    import jax
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      get_env("MXNET_COMPILE_CACHE_MIN_SECS"))
    # tiny programs matter for the serve ladder: do not skip them on
    # size either
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    # jax latches cache initialization on the FIRST compile: enabling
    # the dir after any jax use in the process (tests, a server that
    # reads config late) would silently cache nothing.  Drop the
    # latch so the next compile re-initializes against the new dir.
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except (ImportError, AttributeError):  # layout drift: import-time
        pass                               # enablement still works
    return True
