"""Imperative autograd.

Reference: ``python/mxnet/autograd.py`` (record:122, pause:146, backward:243,
grad:270) over the C++ tape in ``src/imperative/imperative.cc``
(RecordOp:183, MarkVariables:113, Backward:270).

TPU-native design: the tape records (pure-jax-fn, input entries, params) per
eager op; ``backward`` walks the tape in reverse and gets each node's VJP from
``jax.vjp`` of the same function that ran forward — there is no separately
registered gradient per op, so forward/backward can never disagree.  Compiled
paths (CachedOp / executor) instead differentiate the whole traced program
with one ``jax.vjp``, which XLA fuses end-to-end.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "backward", "grad", "Function",
           "get_symbol"]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
    return _state


class _RecordingScope:
    def __init__(self, recording, training):
        self.r = recording
        self.t = training

    def __enter__(self):
        st = _st()
        self.prev = (st.recording, st.training)
        if self.r is not None:
            st.recording = self.r
        if self.t is not None:
            st.training = self.t
        return self

    def __exit__(self, *exc):
        st = _st()
        st.recording, st.training = self.prev


def record(train_mode=True):
    """Scope that turns on tape recording (and train mode by default)."""
    return _RecordingScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingScope(False, train_mode)


def train_mode():
    return _RecordingScope(None, True)


def predict_mode():
    return _RecordingScope(None, False)


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(flag):
    prev = _st().recording
    _st().recording = bool(flag)
    return prev


def set_training(flag):
    prev = _st().training
    _st().training = bool(flag)
    return prev


# ---------------------------------------------------------------------------
# tape
# ---------------------------------------------------------------------------

class TapeNode:
    """One recorded eager op invocation."""

    __slots__ = ("fn", "inputs", "in_entries", "out_arrays", "n_out", "seq",
                 "rng", "op_ref", "dyn")

    def __init__(self, fn, inputs, in_entries, out_arrays, seq, rng=None,
                 op_ref=None, dyn=None):
        self.fn = fn                # pure fn(*arrays) -> tuple(arrays)
        self.inputs = inputs        # raw input jax arrays (forward snapshot)
        self.in_entries = in_entries  # per-input: (TapeNode, out_idx) | leaf | None
        self.out_arrays = out_arrays
        self.n_out = len(out_arrays)
        self.seq = seq
        self.rng = rng
        # op_ref: (op_name, frozen_static_params, dyn_names) enabling the
        # cached jitted VJP path (ops.registry.vjp_jit) — without it the
        # node falls back to re-tracing jax.vjp, which is correct but slow
        # on TPU (per-step retrace)
        self.op_ref = op_ref
        self.dyn = dyn or {}


class Leaf:
    """A marked variable (attach_grad / mark_variables)."""

    __slots__ = ("array", "grad_nd", "grad_req")

    def __init__(self, array, grad_nd, grad_req="write"):
        self.array = array
        self.grad_nd = grad_nd
        self.grad_req = grad_req


_seq_counter = [0]


def record_op(fn, nd_inputs, nd_outputs, rng=None, op_ref=None, dyn=None):
    """Called by the NDArray dispatcher for every op executed while
    recording.  Attaches a tape entry to each output NDArray."""
    in_entries = [getattr(x, "_tape_entry", None) for x in nd_inputs]
    if not any(e is not None for e in in_entries):
        return
    _seq_counter[0] += 1
    node = TapeNode(fn, [x._data for x in nd_inputs], in_entries,
                    [o._data for o in nd_outputs], _seq_counter[0], rng,
                    op_ref=op_ref, dyn=dyn)
    for i, o in enumerate(nd_outputs):
        o._tape_entry = (node, i)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to variables
    (reference: imperative.cc MarkVariables)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._tape_entry = Leaf(v._data, g, req)
        v._grad = g


def _collect(heads):
    """Reachable tape nodes from head entries, sorted by seq desc."""
    nodes = {}
    stack = []
    for h in heads:
        e = getattr(h, "_tape_entry", None)
        if isinstance(e, tuple):
            stack.append(e[0])
    while stack:
        n = stack.pop()
        if id(n) in nodes:
            continue
        nodes[id(n)] = n
        for e in n.in_entries:
            if isinstance(e, tuple):
                stack.append(e[0])
    return sorted(nodes.values(), key=lambda n: n.seq, reverse=True)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True,
             _capture=None):
    """Compute gradients of *heads* w.r.t. every marked variable reachable
    on the tape, accumulating into the attached grad buffers.

    ``_capture``: optional ``(keys: dict[(node_id, out_idx)] -> slot,
    results: list)`` used by :func:`grad` to read cotangents at interior
    graph entries."""
    from .ndarray import NDArray
    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]

    nodes = _collect(heads)
    # cotangent accumulator: (id(node), out_idx) -> jax array
    cots = {}
    leaf_cots = {}  # id(leaf) -> [leaf, accumulated cotangent] this pass
    for h, hg in zip(heads, head_grads):
        e = getattr(h, "_tape_entry", None)
        if e is None:
            continue
        g = hg._data if hg is not None else jnp.ones_like(h._data)
        if isinstance(e, Leaf):
            slot = leaf_cots.setdefault(id(e), [e, None])
            slot[1] = g if slot[1] is None else slot[1] + g
            continue
        node, idx = e
        key = (id(node), idx)
        cots[key] = cots[key] + g if key in cots else g

    cap_keys, cap_results = _capture if _capture is not None else ({}, [])
    for node in nodes:
        outs = [cots.pop((id(node), i), None) for i in range(node.n_out)]
        for i, o in enumerate(outs):
            k = (id(node), i)
            if o is not None and k in cap_keys:
                slot = cap_keys[k]
                cap_results[slot] = o if cap_results[slot] is None \
                    else cap_results[slot] + o
        if all(o is None for o in outs):
            continue
        outs = [o if o is not None else jnp.zeros_like(a)
                for o, a in zip(outs, node.out_arrays)]
        in_cots = _node_vjp(node, outs)
        for e, g in zip(node.in_entries, in_cots):
            if e is None or g is None:
                continue
            if isinstance(e, Leaf):
                slot = leaf_cots.setdefault(id(e), [e, None])
                slot[1] = g if slot[1] is None else slot[1] + g
            else:
                sub, idx = e
                key = (id(sub), idx)
                cots[key] = cots[key] + g if key in cots else g
        if not retain_graph:
            node.in_entries = [None] * len(node.in_entries)

    for leaf, g in leaf_cots.values():
        if g is not None:
            _leaf_accumulate(leaf, g)

    if not retain_graph:
        for h in heads:
            if isinstance(getattr(h, "_tape_entry", None), tuple):
                h._tape_entry = None


def _leaf_accumulate(leaf, g):
    gnd = leaf.grad_nd
    if gnd is None:
        return
    g = g.astype(gnd._data.dtype) if g.dtype != gnd._data.dtype else g
    if leaf.grad_req == "add":
        gnd._data = gnd._data + g.reshape(gnd._data.shape)
    elif leaf.grad_req != "null":
        gnd._data = g.reshape(gnd._data.shape)


def _node_vjp(node, out_cots):
    """VJP of one tape node: cached jitted VJP when the node carries an
    op_ref, else re-linearize the pure fn."""
    if node.op_ref is not None:
        from .ops import registry as _reg
        op_name, frozen, dyn_names = node.op_ref
        fn = _reg.vjp_jit(op_name, frozen, dyn_names, node.rng is not None)
        cots = []
        for c, o in zip(out_cots, node.out_arrays):
            cots.append(c.astype(o.dtype) if c.dtype != o.dtype else c)
        return fn(tuple(node.inputs), node.dyn, node.rng,
                  tuple(cots))

    def fwd(*arrays):
        if node.rng is not None:
            out = node.fn(node.rng, *arrays)
        else:
            out = node.fn(*arrays)
        return out if isinstance(out, tuple) else (out,)

    _, vjp_fn = jax.vjp(fwd, *node.inputs)
    cots = []
    for c, o in zip(out_cots, node.out_arrays):
        cots.append(c.astype(o.dtype) if c.dtype != o.dtype else c)
    return vjp_fn(tuple(cots))


def _node_vjp_recorded(node, out_cot_nds):
    """VJP of one tape node, executed as a *recorded* eager op so the
    resulting cotangents are themselves differentiable (create_graph).

    The node's VJP is re-expressed as a pure jax function of BOTH the
    primals and the output cotangents — ``jax.vjp`` of that function is
    the second-order rule, so grad-of-grad needs no per-op machinery."""
    from .ndarray import NDArray
    if isinstance(node.fn, tuple) and node.fn[0] == "__custom__":
        raise NotImplementedError(
            "create_graph=True through a custom autograd.Function: the "
            "Python backward callback is opaque to the tape")
    n_in = len(node.inputs)
    out_dtypes = [o.dtype for o in node.out_arrays]
    rng = node.rng
    node_fn = node.fn
    if node_fn is None:
        # registry op: rebuild the pure fn from (op, static, dyn) params
        from .ops import registry as _reg
        op_name, frozen, _dyn_names = node.op_ref
        _op = _reg.get_op(op_name)
        _sparams = {k: v for k, v in frozen}
        _dyn = dict(node.dyn)
        if rng is not None:
            def node_fn(r, *p):
                return _op.fn(r, *p, **_sparams, **_dyn)
        else:
            def node_fn(*p):
                return _op.fn(*p, **_sparams, **_dyn)

    def vjp_pure(*arrays):
        primals, cots = arrays[:n_in], arrays[n_in:]

        def fwd(*p):
            out = node_fn(rng, *p) if rng is not None else node_fn(*p)
            return out if isinstance(out, tuple) else (out,)

        _, fv = jax.vjp(fwd, *primals)
        cots = tuple(c.astype(d) if c.dtype != d else c
                     for c, d in zip(cots, out_dtypes))
        res = fv(cots)
        return tuple(r if r is not None else jnp.zeros_like(p)
                     for r, p in zip(res, primals))

    nd_inputs = []
    for arr, e in zip(node.inputs, node.in_entries):
        nd = NDArray(arr)
        nd._tape_entry = e
        nd_inputs.append(nd)
    nd_inputs.extend(out_cot_nds)
    raw = vjp_pure(*[x._data for x in nd_inputs])
    nd_outs = [NDArray(r) for r in raw]
    record_op(vjp_pure, nd_inputs, nd_outs)
    return nd_outs


def _backward_recorded(heads, head_grads, entry_slots, leaf_slots, n_slots):
    """Tape walk mirroring :func:`backward` but carried out on NDArrays
    with every VJP recorded, so returned cotangents stay on the tape.

    ``entry_slots``: {(id(node), out_idx): slot}; ``leaf_slots``:
    {id(leaf): slot}.  Returns a list of NDArray (or None) per slot."""
    from .ndarray import NDArray
    nodes = _collect(heads)
    cots = {}       # (id(node), out_idx) -> NDArray
    leaf_cots = {}  # id(leaf) -> NDArray
    results = [None] * n_slots

    def acc(d, k, g):
        d[k] = d[k] + g if d.get(k) is not None else g

    with record():
        for h, hg in zip(heads, head_grads):
            e = getattr(h, "_tape_entry", None)
            if e is None:
                continue
            g = hg if hg is not None else NDArray(jnp.ones_like(h._data))
            if isinstance(e, Leaf):
                acc(leaf_cots, id(e), g)
            else:
                acc(cots, (id(e[0]), e[1]), g)

        for node in nodes:
            outs = [cots.pop((id(node), i), None)
                    for i in range(node.n_out)]
            for i, o in enumerate(outs):
                k = (id(node), i)
                if o is not None and k in entry_slots:
                    s = entry_slots[k]
                    results[s] = o if results[s] is None else results[s] + o
            if all(o is None for o in outs):
                continue
            outs = [o if o is not None else NDArray(jnp.zeros_like(a))
                    for o, a in zip(outs, node.out_arrays)]
            in_cots = _node_vjp_recorded(node, outs)
            for e, g in zip(node.in_entries, in_cots):
                if e is None or g is None:
                    continue
                if isinstance(e, Leaf):
                    acc(leaf_cots, id(e), g)
                else:
                    acc(cots, (id(e[0]), e[1]), g)

    for lid, slot in leaf_slots.items():
        if leaf_cots.get(lid) is not None:
            results[slot] = leaf_cots[lid]
    return results


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Functional-style gradient (reference: autograd.py:270).

    With ``create_graph=True`` the backward pass itself is recorded on
    the tape, so the returned gradients can be differentiated again
    (grad-of-grad) — each tape node's VJP runs as a recorded pure-jax op
    (see :func:`_node_vjp_recorded`)."""
    from .ndarray import NDArray, zeros_like
    single = isinstance(variables, NDArray)
    if single:
        variables = [variables]
    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]

    def _entry_of(v):
        e = getattr(v, "_tape_entry", None)
        if e is None:
            raise ValueError(
                "cannot take gradient w.r.t. an array that is not on the "
                "tape (call attach_grad() / use it under record())")
        return e

    if create_graph:
        entry_slots, leaf_slots = {}, {}
        for i, v in enumerate(variables):
            e = _entry_of(v)
            if isinstance(e, Leaf):
                leaf_slots[id(e)] = i
            else:
                entry_slots[(id(e[0]), e[1])] = i
        results = _backward_recorded(heads, head_grads, entry_slots,
                                     leaf_slots, len(variables))
        out = [r if r is not None else zeros_like(v)
               for r, v in zip(results, variables)]
        return out[0] if single else out
    cap_keys = {}
    results = [None] * len(variables)
    leaf_bufs = {}
    saved_leaf_grads = {}
    for i, v in enumerate(variables):
        e = _entry_of(v)
        if isinstance(e, Leaf):
            saved_leaf_grads[i] = (e, e.grad_nd, e.grad_req)
            buf = zeros_like(v)
            e.grad_nd = buf
            e.grad_req = "add"
            leaf_bufs[i] = buf
        else:
            cap_keys[(id(e[0]), e[1])] = i
    try:
        backward(heads, head_grads,
                 retain_graph=True if retain_graph is None else retain_graph,
                 train_mode=train_mode, _capture=(cap_keys, results))
    finally:
        for i, (leaf, gnd, req) in saved_leaf_grads.items():
            leaf.grad_nd = gnd
            leaf.grad_req = req
    out = []
    for i, v in enumerate(variables):
        if i in leaf_bufs:
            out.append(leaf_bufs[i])
        else:
            out.append(NDArray(results[i]) if results[i] is not None
                       else zeros_like(v))
    return out[0] if single else out


class Function:
    """Custom differentiable function (reference: autograd.py:363).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` in terms of NDArrays.
    """

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray import NDArray
        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        if is_recording():
            func = self
            _seq_counter[0] += 1
            node = TapeNode(None, [x._data for x in inputs],
                            [getattr(x, "_tape_entry", None) for x in inputs],
                            [o._data for o in outs], _seq_counter[0])

            def custom_vjp(out_cots):
                grads = func.backward(*[NDArray(c) for c in out_cots])
                if isinstance(grads, NDArray):
                    grads = [grads]
                return [g._data if g is not None else None for g in grads]

            node.fn = ("__custom__", custom_vjp)
            for i, o in enumerate(outs):
                o._tape_entry = (node, i)
        return outs[0] if single else outs


# hook custom Function nodes into the vjp path
_orig_node_vjp = _node_vjp


def _node_vjp(node, out_cots):  # noqa: F811
    if isinstance(node.fn, tuple) and node.fn[0] == "__custom__":
        return node.fn[1](out_cots)
    return _orig_node_vjp(node, out_cots)


def get_symbol(x):
    raise NotImplementedError(
        "autograd.get_symbol is not supported; trace with sym.var + "
        "symbolic ops instead")
