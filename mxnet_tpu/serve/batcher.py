"""DynamicBatcher — continuous batching over AOT bucket programs.

Callers submit single examples (or small batches) and get a future;
a dispatcher thread coalesces whatever is queued up to the bucket
capacity or a max-wait deadline, runs ONE padded-bucket XLA dispatch
for the whole group, and resolves each caller's future with its own
row slice.  One program execution serves many callers — the
throughput side of the serving story, with the ladder keeping the
latency side (no compiles) honest.

Concurrency discipline: every lock/condition/thread comes from the
:mod:`..sanitizer` factories, so a ``pytest --graftsan`` run audits
the batcher's locking like any other subsystem, and all deadlines run
on ``time.monotonic`` (graftlint JG012).
"""

from __future__ import annotations

import collections
import time as _time

from .buckets import ServeError
from .. import sanitizer as _san
from ..observability import metrics as _obs_metrics

__all__ = ["ServeFuture", "DynamicBatcher"]

# module-level instrument refs (hot path discipline, see metrics.py)
_REQUEST_SECONDS = _obs_metrics.histogram(
    "serve_request_seconds",
    "end-to-end request latency: submit to future resolution "
    "(queue wait + batching + dispatch)")
_QUEUE_DEPTH = _obs_metrics.gauge(
    "serve_queue_depth",
    "requests waiting across all dynamic batchers (delta-maintained)")
_BATCH_OCCUPANCY = _obs_metrics.histogram(
    "serve_batch_occupancy",
    "real rows / bucket capacity per dispatched batch",
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
_BATCHES_TOTAL = _obs_metrics.counter(
    "serve_batches_total", "coalesced batches dispatched")
_REQUESTS_TOTAL = _obs_metrics.counter(
    "serve_requests_total", "requests submitted to dynamic batchers")


class ServeFuture:
    """Per-caller handle for one submitted request.

    Single-writer (the dispatcher resolves it exactly once); readers
    synchronize through the event, so result/exception fields need no
    extra lock."""

    __slots__ = ("_event", "_result", "_exc", "_t_enq", "_t_resolved")

    def __init__(self):
        self._event = _san.event()
        self._result = None
        self._exc = None
        self._t_enq = _time.monotonic()
        self._t_resolved = None

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        """The request's outputs as a list of host numpy arrays (rows
        = what was submitted) — results cross the service boundary, so
        the batcher reads each batch back once and hands out row
        views.  Blocks up to *timeout* seconds; raises the dispatch
        error if the batch failed."""
        if not self._event.wait(timeout):
            raise TimeoutError("serve request still pending after %ss"
                               % timeout)
        if self._exc is not None:
            raise self._exc
        return self._result

    def _resolve(self, result=None, exc=None):
        if self._event.is_set():
            return
        self._result = result
        self._exc = exc
        self._t_resolved = _time.monotonic()
        _REQUEST_SECONDS.observe(self._t_resolved - self._t_enq)
        self._event.set()


class _Request:
    __slots__ = ("data", "rows", "future")

    def __init__(self, data, rows, future):
        self.data = data
        self.rows = rows
        self.future = future


class DynamicBatcher:
    """Continuous/dynamic request batching in front of one
    :class:`~mxnet_tpu.serve.predictor.CompiledPredictor`.

    Parameters
    ----------
    predictor : CompiledPredictor
    max_wait_ms : float, optional
        How long the dispatcher holds a non-full batch open for more
        arrivals, measured from the OLDEST queued request (default:
        the ``MXNET_SERVE_MAX_WAIT_MS`` knob).
    max_batch : int, optional
        Coalescing cap in rows (default: the ``MXNET_SERVE_MAX_BATCH``
        knob, 0 = the ladder's top rung).
    """

    def __init__(self, predictor, max_wait_ms=None, max_batch=None,
                 name=None):
        from ..config import get_env
        self._predictor = predictor
        self.name = name or predictor.name
        if max_wait_ms is None:
            max_wait_ms = get_env("MXNET_SERVE_MAX_WAIT_MS")
        self._max_wait = max(0.0, float(max_wait_ms)) / 1e3
        if max_batch is None:
            max_batch = get_env("MXNET_SERVE_MAX_BATCH")
        self._max_batch = int(max_batch) or predictor.ladder.max_batch
        if self._max_batch > predictor.ladder.max_batch:
            raise ServeError(
                "max_batch %d exceeds the ladder's top rung %d"
                % (self._max_batch, predictor.ladder.max_batch))
        fixed = set(predictor._data_shapes) - predictor._bucket_inputs
        if fixed:
            raise ServeError(
                "model %r has fixed-shape inputs %s — dynamic batching "
                "concatenates every input along the batch axis; call "
                "predictor.predict directly"
                % (predictor.name, sorted(fixed)))
        self._lock = _san.lock(label="serve.batcher.%s" % self.name)
        self._cond = _san.condition(self._lock,
                                    label="serve.batcher.%s" % self.name)
        self._pending = collections.deque()
        self._rows_pending = 0
        self._stopped = False
        self._batches = 0
        self._requests = 0
        self._thread = _san.thread(
            target=self._loop, name="serve-batcher-%s" % self.name,
            daemon=True)
        _san.track(self, ("_pending", "_rows_pending", "_stopped",
                          "_batches", "_requests"),
                   label="serve.batcher.%s" % self.name)
        self._thread.start()

    # -- stats -------------------------------------------------------------
    @property
    def batch_count(self):
        with self._lock:
            return self._batches

    @property
    def request_count(self):
        with self._lock:
            return self._requests

    # -- client side -------------------------------------------------------
    def submit(self, data):
        """Queue one request ({input: array}, or a bare array for
        single-input models; arrays may be single examples or small
        row batches up to the coalescing cap).  Returns a
        :class:`ServeFuture`."""
        pred = self._predictor
        if not isinstance(data, dict):
            if len(pred._data_shapes) != 1:
                raise ServeError(
                    "model %r has %d inputs — submit a dict"
                    % (pred.name, len(pred._data_shapes)))
            data = {next(iter(pred._data_shapes)): data}
        arrays = {}
        rows = None
        from .predictor import _as_jnp
        for n, spec in pred._data_shapes.items():
            if n not in data:
                raise ServeError("request is missing input %r" % n)
            a = _as_jnp(data[n])
            if a.ndim == len(spec) - 1:
                a = a[None]
            if a.ndim != len(spec):
                raise ServeError(
                    "input %r: rank %d does not match the bound "
                    "example rank %d" % (n, a.ndim, len(spec)))
            if rows is None:
                rows = a.shape[0]
            elif a.shape[0] != rows:
                raise ServeError("request inputs disagree on rows "
                                 "(%d vs %d)" % (a.shape[0], rows))
            arrays[n] = a
        if rows < 1:
            raise ServeError("request has no rows")
        if rows > self._max_batch:
            raise ServeError(
                "request of %d rows exceeds the batcher cap %d — "
                "split it, or call predictor.predict directly"
                % (rows, self._max_batch))
        fut = ServeFuture()
        with self._lock:
            if self._stopped:
                raise ServeError("batcher %r is closed" % self.name)
            self._pending.append(_Request(arrays, rows, fut))
            self._rows_pending += rows
            self._requests += 1
            # delta accounting: the gauge aggregates across batchers
            _QUEUE_DEPTH.inc()
            self._cond.notify()
        _REQUESTS_TOTAL.inc()
        return fut

    def __call__(self, data, timeout=None):
        """Synchronous convenience: submit + wait."""
        return self.submit(data).result(timeout)

    # -- dispatcher --------------------------------------------------------
    def _take_locked(self):
        """Pop the next coalesced group (caller holds the lock)."""
        taken = []
        rows = 0
        while self._pending and \
                rows + self._pending[0].rows <= self._max_batch:
            req = self._pending.popleft()
            # both callers hold self._lock (submit-side writes do too)
            self._rows_pending -= req.rows  # graftlint: disable=JG010
            rows += req.rows
            taken.append(req)
        if taken:
            _QUEUE_DEPTH.dec(len(taken))
        return taken, rows

    def _loop(self):
        import numpy as np
        pred = self._predictor
        while True:
            with self._cond:
                while not self._pending and not self._stopped:
                    self._cond.wait()
                if self._stopped and not self._pending:
                    return
                # hold the batch open for late arrivals until either
                # the rows fill the cap or the OLDEST request's
                # deadline passes (monotonic clock only)
                deadline = self._pending[0].future._t_enq + \
                    self._max_wait
                while self._rows_pending < self._max_batch and \
                        not self._stopped:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                    if not self._pending:
                        break
                taken, rows = self._take_locked()
            if not taken:
                continue
            try:
                stacked = {
                    n: np.concatenate([r.data[n] for r in taken], axis=0)
                    if len(taken) > 1 else taken[0].data[n]
                    for n in pred._data_shapes}
                outs = pred.predict(stacked)
                # ONE device->host readback per coalesced batch; the
                # per-caller row splits below are numpy views.  (Lazy
                # per-request device slices would dispatch — and on
                # first use COMPILE — a tiny XLA program per distinct
                # row range; results are leaving the process anyway.)
                host = [np.asarray(o._data) for o in outs]
                # count successful dispatches only, in lockstep with
                # the serve_batches_total instrument
                with self._lock:
                    self._batches += 1
                _BATCHES_TOTAL.inc()
                _BATCH_OCCUPANCY.observe(
                    rows / float(pred.ladder.batch_for(rows)))
                lo = 0
                for req in taken:
                    hi = lo + req.rows
                    req.future._resolve(result=[
                        h[lo:hi] if h.ndim and h.shape[0] == rows
                        else h for h in host])
                    lo = hi
            except Exception as exc:
                for req in taken:
                    req.future._resolve(exc=exc)

    # -- lifecycle ---------------------------------------------------------
    def close(self, timeout=5.0):
        """Stop the dispatcher.  Queued-but-undispatched requests fail
        with a :class:`ServeError`; the in-flight batch (if any)
        completes."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            orphans = list(self._pending)
            self._pending.clear()
            self._rows_pending = 0
            if orphans:
                _QUEUE_DEPTH.dec(len(orphans))
            self._cond.notify_all()
        for req in orphans:
            req.future._resolve(
                exc=ServeError("batcher %r closed before dispatch"
                               % self.name))
        self._thread.join(timeout)
