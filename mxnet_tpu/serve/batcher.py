"""DynamicBatcher — continuous batching over AOT bucket programs.

Callers submit single examples (or small batches) and get a future;
a dispatcher thread coalesces whatever is queued up to the bucket
capacity or a max-wait deadline, runs ONE padded-bucket XLA dispatch
for the whole group, and resolves each caller's future with its own
row slice.  One program execution serves many callers — the
throughput side of the serving story, with the ladder keeping the
latency side (no compiles) honest.

Fault-tolerance discipline (the request-path mirror of the training
stack's PR 3/7/8 machinery):

* **Admission control / load shedding** — the queue is bounded in
  requests (``MXNET_SERVE_MAX_QUEUE``) and bytes
  (``MXNET_SERVE_MAX_QUEUE_BYTES``); a submit past either cap raises
  a typed :class:`~mxnet_tpu.serve.buckets.OverloadError` instead of
  queueing unboundedly.
* **Deadlines** — ``submit(data, deadline_ms=...)`` (default
  ``MXNET_SERVE_DEFAULT_DEADLINE_MS``) propagates into the
  dispatcher: an expired request is shed BEFORE padding/dispatch and
  resolves with :class:`DeadlineExceededError`; a caller that gives
  up client-side calls :meth:`ServeFuture.cancel` to reclaim its
  queue slot rather than riding a dead row through XLA.
* **Dispatcher supervision** — a dispatch failure fails only that
  batch's futures; an exception ESCAPING the loop fails exactly the
  in-flight batch, then restarts the thread with the shared jittered
  backoff, bounded by ``MXNET_SERVE_DISPATCHER_RESTARTS``; past the
  budget the batcher marks itself unhealthy and fails every queued
  future loudly.
* **Graceful drain** — :meth:`drain` stops admissions and waits
  (bounded) for accepted work; :meth:`close` that cannot join the
  dispatcher surfaces ``closed_dirty`` instead of returning as if
  clean.

Concurrency discipline: every lock/condition/thread comes from the
:mod:`..sanitizer` factories, so a ``pytest --graftsan`` run audits
the batcher's locking like any other subsystem, and all deadlines run
on ``time.monotonic`` (graftlint JG012).
"""

from __future__ import annotations

import collections
import logging
import random
import time as _time

from .buckets import (DeadlineExceededError, OverloadError,
                      RequestCancelled, ServeError)
from .. import sanitizer as _san
from ..observability import events as _obs_events
from ..observability import metrics as _obs_metrics
from ..resilience import servechaos as _servechaos
from ..resilience.retry import backoff_delays

__all__ = ["ServeFuture", "DynamicBatcher"]

log = logging.getLogger(__name__)

# module-level instrument refs (hot path discipline, see metrics.py)
_REQUEST_SECONDS = _obs_metrics.histogram(
    "serve_request_seconds",
    "end-to-end request latency: submit to future resolution "
    "(queue wait + batching + dispatch)")
_QUEUE_DEPTH = _obs_metrics.gauge(
    "serve_queue_depth",
    "requests waiting across all dynamic batchers (delta-maintained)")
_QUEUE_AGE = _obs_metrics.histogram(
    "serve_queue_age_seconds",
    "how long each request waited in the batcher queue before being "
    "taken for dispatch")
_BATCH_OCCUPANCY = _obs_metrics.histogram(
    "serve_batch_occupancy",
    "real rows / bucket capacity per dispatched batch",
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
_BATCHES_TOTAL = _obs_metrics.counter(
    "serve_batches_total", "coalesced batches dispatched")
_REQUESTS_TOTAL = _obs_metrics.counter(
    "serve_requests_total", "requests submitted to dynamic batchers")
_SHED_TOTAL = _obs_metrics.counter(
    "serve_requests_shed_total",
    "requests rejected at submit time by admission control "
    "(queue request/byte caps, draining, unhealthy)")
_EXPIRED_TOTAL = _obs_metrics.counter(
    "serve_requests_expired_total",
    "requests whose deadline passed before dispatch — shed by the "
    "dispatcher BEFORE padding, never sent through XLA")
_CANCELLED_TOTAL = _obs_metrics.counter(
    "serve_requests_cancelled_total",
    "queued requests abandoned by their caller (ServeFuture.cancel) "
    "whose slot was reclaimed before dispatch")
_RESTARTS_TOTAL = _obs_metrics.counter(
    "serve_dispatcher_restarts_total",
    "serve dispatcher threads restarted after a crash escaped the "
    "batching loop")
_DIRTY_CLOSES_TOTAL = _obs_metrics.counter(
    "serve_batcher_dirty_closes_total",
    "batcher closes that could not join the dispatcher thread within "
    "the close timeout (closed_dirty)")


class ServeFuture:
    """Per-caller handle for one submitted request.

    Single-writer (the dispatcher — or the cancel path, arbitrated by
    the batcher lock — resolves it exactly once); readers synchronize
    through the event, so result/exception fields need no extra
    lock."""

    __slots__ = ("_event", "_result", "_exc", "_t_enq", "_t_resolved",
                 "_cancel_cb")

    def __init__(self):
        self._event = _san.event()
        self._result = None
        self._exc = None
        self._t_enq = _time.monotonic()
        self._t_resolved = None
        self._cancel_cb = None

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        """The request's outputs as a list of host numpy arrays (rows
        = what was submitted) — results cross the service boundary, so
        the batcher reads each batch back once and hands out row
        views.  Blocks up to *timeout* seconds; raises the dispatch
        error if the batch failed.  A caller that gives up on a
        ``TimeoutError`` should call :meth:`cancel` so its queue slot
        is reclaimed instead of being padded and dispatched for
        nobody."""
        if not self._event.wait(timeout):
            raise TimeoutError("serve request still pending after %ss"
                               % timeout)
        if self._exc is not None:
            raise self._exc
        return self._result

    def cancel(self):
        """Abandon the request.  True when the queue slot was
        reclaimed before dispatch (the future resolves with
        :class:`RequestCancelled`); False when the request already
        dispatched or resolved — the result is still readable."""
        cb = self._cancel_cb
        if cb is None or self._event.is_set():
            return False
        return cb()

    def _resolve(self, result=None, exc=None):
        if self._event.is_set():
            return
        # drop the cancel closure: it pins the request payload and the
        # batcher (and cycles through req.future) long after resolution
        self._cancel_cb = None
        self._result = result
        self._exc = exc
        self._t_resolved = _time.monotonic()
        _REQUEST_SECONDS.observe(self._t_resolved - self._t_enq)
        self._event.set()


class _Request:
    __slots__ = ("data", "rows", "nbytes", "deadline", "dispatch_by",
                 "future", "taken", "cancelled")

    def __init__(self, data, rows, nbytes, deadline, dispatch_by,
                 future):
        self.data = data
        self.rows = rows
        self.nbytes = nbytes
        self.deadline = deadline      # monotonic, or None
        # when this request heads the queue, its coalescing window
        # closes no later than dispatch_by — a margin BEFORE the
        # deadline, so a deadline-bound head dispatches instead of
        # expiring at the boundary.  Expiry (deadline passed while the
        # dispatcher could not get to the request) stays a _take_locked
        # decision against .deadline itself.
        self.dispatch_by = dispatch_by
        self.future = future
        self.taken = False
        self.cancelled = False


class DynamicBatcher:
    """Continuous/dynamic request batching in front of one
    :class:`~mxnet_tpu.serve.predictor.CompiledPredictor`.

    Parameters
    ----------
    predictor : CompiledPredictor
    max_wait_ms : float, optional
        How long the dispatcher holds a non-full batch open for more
        arrivals, measured from the OLDEST queued request (default:
        the ``MXNET_SERVE_MAX_WAIT_MS`` knob).
    max_batch : int, optional
        Coalescing cap in rows (default: the ``MXNET_SERVE_MAX_BATCH``
        knob, 0 = the ladder's top rung).
    max_queue : int, optional
        Admission cap in queued requests (default
        ``MXNET_SERVE_MAX_QUEUE``; 0 = unbounded).
    max_queue_bytes : int, optional
        Admission cap in queued payload bytes (default
        ``MXNET_SERVE_MAX_QUEUE_BYTES``; 0 = unbounded).
    default_deadline_ms : float, optional
        Deadline applied to submits that pass none (default
        ``MXNET_SERVE_DEFAULT_DEADLINE_MS``; 0 = no deadline).
    max_restarts : int, optional
        Dispatcher crash-restart budget (default
        ``MXNET_SERVE_DISPATCHER_RESTARTS``).
    on_state : callable, optional
        ``on_state(state)`` hook the registry wires to its health
        board; called with ``"unhealthy"`` when the restart budget is
        exhausted.
    tuning : dict, optional
        Per-model tuned knob values (env-var name -> value) from the
        autotune ``TuningStore`` entry the registry attached to the
        predictor at load time (``predictor.tuning``) — consulted for
        every knob the constructor was not given explicitly, BELOW an
        exported env var: explicit argument > exported env > tuned
        store > registered default (docs/autotuning.md).  Default:
        the attached predictor's record.
    """

    def __init__(self, predictor, max_wait_ms=None, max_batch=None,
                 name=None, max_queue=None, max_queue_bytes=None,
                 default_deadline_ms=None, max_restarts=None,
                 on_state=None, tuning=None):
        from ..config import resolve_env
        self._predictor = predictor
        self.name = name or predictor.name
        if tuning is None:
            rec = getattr(predictor, "tuning", None) or {}
            tuning = rec.get("config") or {}
        self._tuning = dict(tuning)
        _tuned = self._tuning.get
        if max_wait_ms is None:
            max_wait_ms = resolve_env("MXNET_SERVE_MAX_WAIT_MS",
                                      _tuned("MXNET_SERVE_MAX_WAIT_MS"))
        self._max_wait = max(0.0, float(max_wait_ms)) / 1e3
        if max_batch is None:
            max_batch = resolve_env("MXNET_SERVE_MAX_BATCH",
                                    _tuned("MXNET_SERVE_MAX_BATCH"))
        self._max_batch = int(max_batch) or predictor.ladder.max_batch
        if self._max_batch > predictor.ladder.max_batch:
            raise ServeError(
                "max_batch %d exceeds the ladder's top rung %d"
                % (self._max_batch, predictor.ladder.max_batch))
        if max_queue is None:
            max_queue = resolve_env("MXNET_SERVE_MAX_QUEUE",
                                    _tuned("MXNET_SERVE_MAX_QUEUE"))
        self._max_queue = max(0, int(max_queue))
        if max_queue_bytes is None:
            max_queue_bytes = resolve_env(
                "MXNET_SERVE_MAX_QUEUE_BYTES",
                _tuned("MXNET_SERVE_MAX_QUEUE_BYTES"))
        self._max_queue_bytes = max(0, int(max_queue_bytes))
        if default_deadline_ms is None:
            default_deadline_ms = resolve_env(
                "MXNET_SERVE_DEFAULT_DEADLINE_MS",
                _tuned("MXNET_SERVE_DEFAULT_DEADLINE_MS"))
        self._default_deadline = max(0.0, float(default_deadline_ms)) / 1e3
        if max_restarts is None:
            max_restarts = resolve_env(
                "MXNET_SERVE_DISPATCHER_RESTARTS",
                _tuned("MXNET_SERVE_DISPATCHER_RESTARTS"))
        self._max_restarts = max(0, int(max_restarts))
        self._on_state = on_state
        fixed = set(predictor._data_shapes) - predictor._bucket_inputs
        if fixed:
            raise ServeError(
                "model %r has fixed-shape inputs %s — dynamic batching "
                "concatenates every input along the batch axis; call "
                "predictor.predict directly"
                % (predictor.name, sorted(fixed)))
        self._lock = _san.lock(label="serve.batcher.%s" % self.name)
        self._cond = _san.condition(self._lock,
                                    label="serve.batcher.%s" % self.name)
        self._pending = collections.deque()
        self._rows_pending = 0
        self._bytes_pending = 0
        self._flush_horizon = 0.0
        self._inflight = ()
        self._stopped = False
        self._draining = False
        self._unhealthy = False
        self._closed_dirty = False
        self._batches = 0
        self._requests = 0
        self._restarts_used = 0
        self._last_drain_stats = None
        self._last_tick = _time.monotonic()
        # the shared jittered backoff schedule of resilience.retry;
        # one delay per crash-restart (tests patch _restart_sleep)
        self._backoff = backoff_delays(
            self._max_restarts + 1, base_delay=0.05, max_delay=2.0,
            multiplier=2.0, jitter=0.5, rng=random.Random())
        self._restart_sleep = _time.sleep
        self._thread = _san.thread(
            target=self._run, name="serve-batcher-%s" % self.name,
            daemon=True)
        _san.track(self, ("_pending", "_rows_pending", "_bytes_pending",
                          "_flush_horizon", "_inflight", "_stopped",
                          "_draining", "_unhealthy", "_closed_dirty",
                          "_batches", "_requests", "_restarts_used",
                          "_last_drain_stats"),
                   label="serve.batcher.%s" % self.name)
        self._thread.start()

    # -- stats / health ----------------------------------------------------
    @property
    def batch_count(self):
        with self._lock:
            return self._batches

    @property
    def request_count(self):
        with self._lock:
            return self._requests

    @property
    def queue_depth(self):
        with self._lock:
            return len(self._pending)

    @property
    def restart_count(self):
        with self._lock:
            return self._restarts_used

    @property
    def unhealthy(self):
        with self._lock:
            return self._unhealthy

    @property
    def draining(self):
        with self._lock:
            return self._draining

    @property
    def closed_dirty(self):
        with self._lock:
            return self._closed_dirty

    def _accepted_locked(self):
        """Requests the batcher currently OWES an answer: queued (not
        cancelled) plus the in-flight batch.  Caller holds the lock."""
        return (sum(1 for r in self._pending if not r.cancelled)
                + len(self._inflight))

    @property
    def accepted_count(self):
        """The work a drain would have to wait on, right now."""
        with self._lock:
            return self._accepted_locked()

    @property
    def last_drain_stats(self):
        """Machine-readable record of the most recent :meth:`drain`:
        ``{"waited_requests": N, "timed_out": bool}`` (None before
        any drain).  The registry's ``drain_complete`` event and the
        fleet's rolling deploy gate on this instead of inferring
        'drain completed with zero abandoned work' from counters."""
        with self._lock:
            return dict(self._last_drain_stats) \
                if self._last_drain_stats is not None else None

    def dispatcher_alive(self):
        """Is the dispatcher thread running (restarts included)?"""
        with self._lock:
            thread, unhealthy = self._thread, self._unhealthy
        return bool(thread.is_alive()) and not unhealthy

    def last_tick_age(self):
        """Seconds since the dispatcher last ticked its liveness
        stamp.  The loop ticks at least every ~0.5s even when idle, so
        a large age with work pending means a wedged dispatch (the
        health surface's hang signal)."""
        with self._lock:
            return _time.monotonic() - self._last_tick

    def health_state(self):
        """The batcher's own contribution to the model health state
        machine: ``unhealthy`` / ``draining`` / ``ready``."""
        with self._lock:
            if self._unhealthy:
                return "unhealthy"
            if self._stopped or self._draining:
                return "draining"
            return "ready"

    # -- client side -------------------------------------------------------
    def submit(self, data, deadline_ms=None):
        """Queue one request ({input: array}, or a bare array for
        single-input models; arrays may be single examples or small
        row batches up to the coalescing cap).  Returns a
        :class:`ServeFuture`.

        *deadline_ms* bounds how long the request may WAIT: the
        coalescing window never holds a head past its deadline (the
        dispatcher cuts the window short and dispatches with margin to
        spare), and a request the dispatcher could not reach in time —
        backlog ahead of it, a slow or wedged dispatch — is shed
        (typed :class:`DeadlineExceededError`) instead of padded and
        dispatched as a row nobody wants.  ``None`` applies the
        ``MXNET_SERVE_DEFAULT_DEADLINE_MS`` knob; 0 there = no
        deadline.  Raises :class:`OverloadError` when the queue is at
        its request or byte cap — overload sheds at the front door."""
        pred = self._predictor
        if not isinstance(data, dict):
            if len(pred._data_shapes) != 1:
                raise ServeError(
                    "model %r has %d inputs — submit a dict"
                    % (pred.name, len(pred._data_shapes)))
            data = {next(iter(pred._data_shapes)): data}
        arrays = {}
        rows = None
        nbytes = 0
        from .predictor import _as_jnp
        for n, spec in pred._data_shapes.items():
            if n not in data:
                raise ServeError("request is missing input %r" % n)
            a = _as_jnp(data[n])
            if a.ndim == len(spec) - 1:
                a = a[None]
            if a.ndim != len(spec):
                raise ServeError(
                    "input %r: rank %d does not match the bound "
                    "example rank %d" % (n, a.ndim, len(spec)))
            if rows is None:
                rows = a.shape[0]
            elif a.shape[0] != rows:
                raise ServeError("request inputs disagree on rows "
                                 "(%d vs %d)" % (a.shape[0], rows))
            arrays[n] = a
            nbytes += int(a.nbytes)
        if rows < 1:
            raise ServeError("request has no rows")
        if rows > self._max_batch:
            raise ServeError(
                "request of %d rows exceeds the batcher cap %d — "
                "split it, or call predictor.predict directly"
                % (rows, self._max_batch))
        if deadline_ms is not None and float(deadline_ms) <= 0:
            raise ServeError("deadline_ms must be > 0, got %r"
                             % (deadline_ms,))
        budget = (float(deadline_ms) / 1e3 if deadline_ms is not None
                  else self._default_deadline)
        if budget > 0:
            now = _time.monotonic()
            deadline = now + budget
            # reserve up to 50ms (never more than a quarter of the
            # budget) of dispatch headroom: the window a deadline
            # closes must close BEFORE the deadline, or the head would
            # always wake exactly expired
            dispatch_by = deadline - min(0.05, budget * 0.25)
        else:
            deadline = dispatch_by = None
        fut = req = None         # allocated only if admission passes —
        shed_reason = err = None  # the shed path is the overload-hot one
        with self._lock:
            if self._stopped:
                raise ServeError("batcher %r is closed" % self.name)
            if self._unhealthy:
                shed_reason, err = "unhealthy", ServeError(
                    "batcher %r is unhealthy (dispatcher failed past "
                    "its %d-restart budget)" % (self.name,
                                                self._max_restarts))
            elif self._draining:
                shed_reason, err = "draining", ServeError(
                    "batcher %r is draining — admissions are stopped"
                    % self.name)
            elif self._max_queue and \
                    len(self._pending) >= self._max_queue:
                shed_reason, err = "max_queue", OverloadError(
                    "batcher %r queue is full (%d requests, cap %d) — "
                    "shedding at submit" % (self.name,
                                            len(self._pending),
                                            self._max_queue))
            elif self._max_queue_bytes and \
                    self._bytes_pending + nbytes > self._max_queue_bytes:
                shed_reason, err = "max_queue_bytes", OverloadError(
                    "batcher %r queue is at its byte cap (%d + %d > %d)"
                    % (self.name, self._bytes_pending, nbytes,
                       self._max_queue_bytes))
            else:
                fut = ServeFuture()
                req = _Request(arrays, rows, nbytes, deadline,
                               dispatch_by, fut)
                # wire the cancel hook BEFORE the dispatcher can see
                # the request (same lock): assigning after release
                # would re-pin a payload _resolve already dropped
                fut._cancel_cb = lambda: self._cancel(req)
                self._pending.append(req)
                self._rows_pending += rows
                self._bytes_pending += nbytes
                self._requests += 1
                # delta accounting: the gauge aggregates across batchers
                _QUEUE_DEPTH.inc()
                self._cond.notify()
        if shed_reason is not None:
            # counter bump + event-file write happen OUTSIDE the lock:
            # during an overload storm this path is the hot one, and
            # I/O under the lock would serialize every submitter and
            # the dispatcher behind the events fd
            self._shed(shed_reason)
            raise err
        _REQUESTS_TOTAL.inc()
        return fut

    def detach_state_hook(self):
        """Unwire the on_state health hook.  The registry calls this
        when the batcher is displaced (load-replace) or its model
        unloaded, so a late dispatcher crash cannot mark the board
        entry now owned by a healthy replacement — or resurrect a
        dropped one."""
        self._on_state = None

    def _shed(self, reason):
        """Account one shed admission (called after the lock is
        released; the caller raises the typed error itself)."""
        _SHED_TOTAL.inc()
        _obs_events.emit("serve", kind="shed", model=self.name,
                         reason=reason)

    def _cancel(self, req):
        """ServeFuture.cancel target: reclaim *req*'s queue slot if it
        has not been taken for dispatch."""
        with self._lock:
            if req.taken or req.cancelled or req.future.done():
                return False
            try:
                self._pending.remove(req)
            except ValueError:
                # unreachable today: every path that removes a pending
                # request marks it taken/cancelled under this lock and
                # the guard above returns False for those.  Never fall
                # through to the accounting — that would re-decrement
                # a slot someone else already settled.
                return False
            req.cancelled = True
            self._rows_pending -= req.rows
            self._bytes_pending -= req.nbytes
            _QUEUE_DEPTH.dec()
            # wake the dispatcher (a cancelled head must not pin the
            # coalescing window of whatever queued behind it) AND any
            # drain() waiter this cancellation may have unblocked
            self._cond.notify_all()
        _CANCELLED_TOTAL.inc()
        _obs_events.emit("serve", kind="cancelled", model=self.name,
                         rows=req.rows)
        req.future._resolve(exc=RequestCancelled(
            "request cancelled by its caller before dispatch "
            "(batcher %r)" % self.name))
        return True

    def __call__(self, data, timeout=None):
        """Synchronous convenience: submit + wait."""
        return self.submit(data).result(timeout)

    # -- dispatcher --------------------------------------------------------
    def _take_locked(self):
        """Pop the next coalesced group (caller holds the lock).
        Cancelled slots are discarded; expired requests are shed here,
        BEFORE any padding or dispatch, and returned for resolution
        outside the lock."""
        taken = []
        expired = []
        rows = 0
        now = _time.monotonic()
        while self._pending:
            req = self._pending[0]
            if req.cancelled:
                # accounting already done by _cancel
                self._pending.popleft()
                continue
            if req.deadline is not None and now >= req.deadline:
                self._pending.popleft()
                # taken = "off the queue, accounting settled, the
                # batcher owns resolution" — set under the lock so a
                # racing ServeFuture.cancel() cannot re-decrement the
                # rows/bytes/depth accounting or double-resolve
                req.taken = True
                # both callers hold self._lock (submit writes do too)
                self._rows_pending -= req.rows  # graftlint: disable=JG010
                self._bytes_pending -= req.nbytes  # graftlint: disable=JG010
                expired.append(req)
                continue
            if rows + req.rows > self._max_batch:
                break
            self._pending.popleft()
            self._rows_pending -= req.rows  # graftlint: disable=JG010
            self._bytes_pending -= req.nbytes  # graftlint: disable=JG010
            req.taken = True
            rows += req.rows
            taken.append(req)
            _QUEUE_AGE.observe(now - req.future._t_enq)
        shed = len(taken) + len(expired)
        if shed:
            _QUEUE_DEPTH.dec(shed)
        return taken, rows, expired

    def _run(self):
        """Dispatcher thread body: the batching loop under
        supervision.  A dispatch failure is handled INSIDE the loop
        (only that batch's futures fail); anything escaping it lands
        here and goes through crash handling — fail exactly the
        in-flight batch, restart with backoff within the budget, or
        go unhealthy and fail everything queued, loudly."""
        try:
            self._loop()
        except Exception as exc:
            self._dispatcher_crashed(exc)

    def _loop(self):
        import numpy as np
        pred = self._predictor
        while True:
            with self._cond:
                self._last_tick = _time.monotonic()
                while not self._pending and not self._stopped:
                    # bounded idle wait so the liveness tick stays
                    # fresh even with no traffic (health surface)
                    self._cond.wait(timeout=0.5)
                    self._last_tick = _time.monotonic()
                if self._stopped and not self._pending:
                    return
                # hold the batch open for late arrivals until the rows
                # fill the cap, the OLDEST request's max-wait window
                # closes, or its deadline approaches (monotonic clock
                # only); a draining batcher dispatches immediately.
                # The head is re-derived every iteration: a cancelled
                # or expired head hands the window to its successor
                # instead of pinning it.
                while not self._stopped and not self._draining and \
                        self._pending:
                    head = self._pending[0]
                    if head.cancelled:
                        # defensive: _cancel removes cancelled requests
                        # from the queue under this lock, so this is
                        # unreachable today — but discarding inline
                        # keeps the successor's own window intact
                        # rather than dispatching it immediately
                        self._pending.popleft()
                        continue
                    if head.future._t_enq <= self._flush_horizon:
                        break       # flushed: dispatch without waiting
                    now = _time.monotonic()
                    window = head.future._t_enq + self._max_wait
                    # any queued request that FITS this batch closes
                    # the window EARLY at its dispatch-before-deadline
                    # margin — not just the head's, or a tight-deadline
                    # request behind a deadline-less head would expire
                    # on an idle server.  A request only expires when
                    # the dispatcher could not get to it by then
                    # (backlog, wedged dispatch).
                    fit = 0
                    for r in self._pending:
                        if r.cancelled:
                            continue
                        if fit + r.rows > self._max_batch:
                            break
                        fit += r.rows
                        if r.dispatch_by is not None:
                            window = min(window, r.dispatch_by)
                    if self._rows_pending >= self._max_batch or \
                            now >= window:
                        break
                    self._cond.wait(timeout=window - now)
                    self._last_tick = _time.monotonic()
                taken, rows, expired = self._take_locked()
                if taken:
                    self._inflight = tuple(taken)
                elif not self._pending:
                    # a shed-only round (expired / cancelled heads) can
                    # empty the queue without ever reaching the
                    # dispatch path's notify — wake drain()/flush()
                    # waiters now instead of letting them sleep out
                    # their full timeout
                    self._cond.notify_all()
            for req in expired:
                _EXPIRED_TOTAL.inc()
                _obs_events.emit("serve", kind="expired",
                                 model=self.name, rows=req.rows)
                req.future._resolve(exc=DeadlineExceededError(
                    "request expired after %.3fs in the %r queue — "
                    "shed before dispatch"
                    % (_time.monotonic() - req.future._t_enq,
                       self.name)))
            if not taken:
                continue
            # chaos choke point, deliberately OUTSIDE the per-batch
            # isolation below: an injected raise here escapes the loop
            # and exercises the supervision path (ci/serve_chaos_drill)
            _servechaos.on_dispatch(self.name)
            try:
                stacked = {
                    n: np.concatenate([r.data[n] for r in taken], axis=0)
                    if len(taken) > 1 else taken[0].data[n]
                    for n in pred._data_shapes}
                outs = pred.predict(stacked)
                # ONE device->host readback per coalesced batch; the
                # per-caller row splits below are numpy views.  (Lazy
                # per-request device slices would dispatch — and on
                # first use COMPILE — a tiny XLA program per distinct
                # row range; results are leaving the process anyway.)
                host = [np.asarray(o._data) for o in outs]
                # count successful dispatches only, in lockstep with
                # the serve_batches_total instrument
                with self._lock:
                    self._batches += 1
                _BATCHES_TOTAL.inc()
                _BATCH_OCCUPANCY.observe(
                    rows / float(pred.ladder.batch_for(rows)))
                lo = 0
                for req in taken:
                    hi = lo + req.rows
                    req.future._resolve(result=[
                        h[lo:hi] if h.ndim and h.shape[0] == rows
                        else h for h in host])
                    lo = hi
            except Exception as exc:
                # per-batch isolation: a failed dispatch fails exactly
                # this batch's callers, the loop keeps serving
                for req in taken:
                    req.future._resolve(exc=exc)
            finally:
                with self._cond:
                    self._inflight = ()
                    self._cond.notify_all()    # drain/flush waiters

    def _dispatcher_crashed(self, exc):
        """An exception escaped the batching loop: resolve exactly the
        in-flight batch with it, then restart within the budget or go
        unhealthy (failing everything queued)."""
        with self._cond:
            inflight = self._inflight
            self._inflight = ()
            self._restarts_used += 1
            crashes = self._restarts_used
            give_up = crashes > self._max_restarts or self._stopped
            orphans = ()
            if give_up and not self._stopped:
                self._unhealthy = True
                orphans = tuple(r for r in self._pending
                                if not r.cancelled)
                for r in orphans:
                    r.taken = True  # cancel() races the resolve below
                self._pending.clear()
                self._rows_pending = 0
                self._bytes_pending = 0
                if orphans:
                    _QUEUE_DEPTH.dec(len(orphans))
            stopped = self._stopped
        log.error("serve batcher %r: dispatcher crashed (%s: %s) — "
                  "crash %d/%d-restart budget", self.name,
                  type(exc).__name__, exc, crashes, self._max_restarts)
        for req in inflight:
            # exactly the failing batch gets the crash error
            req.future._resolve(exc=exc)
        if give_up and not stopped:
            err = ServeError(
                "batcher %r is unhealthy: dispatcher crashed %d times "
                "(budget %d); last error: %s: %s"
                % (self.name, crashes, self._max_restarts,
                   type(exc).__name__, exc))
            for req in orphans:
                req.future._resolve(exc=err)
        # wake drain()/flush()/close() waiters only AFTER every future
        # their contract covers is resolved — notifying from the lock
        # block above let drain() return True while the crashed
        # batch's futures were still unset
        with self._cond:
            self._cond.notify_all()
        if stopped:
            return
        if give_up:
            _obs_events.emit("serve", kind="unhealthy", model=self.name,
                             crashes=crashes, failed_queued=len(orphans),
                             error="%s: %s" % (type(exc).__name__,
                                               str(exc)[:200]))
            log.error("serve batcher %r: restart budget exhausted — "
                      "unhealthy, failed %d queued futures", self.name,
                      len(orphans))
            if self._on_state is not None:
                try:
                    self._on_state("unhealthy")
                except Exception:
                    log.exception("serve batcher %r: on_state hook "
                                  "failed", self.name)
            return
        delay = next(self._backoff)
        _RESTARTS_TOTAL.inc()
        _obs_events.emit("serve", kind="dispatcher_restart",
                         model=self.name, restart=crashes,
                         backoff_s=round(delay, 4),
                         error="%s: %s" % (type(exc).__name__,
                                           str(exc)[:200]))
        self._restart_sleep(delay)
        with self._lock:
            if self._stopped:
                return
            self._thread = _san.thread(
                target=self._run,
                name="serve-batcher-%s-r%d" % (self.name, crashes),
                daemon=True)
            self._thread.start()

    # -- lifecycle ---------------------------------------------------------
    def drain(self, timeout=None):
        """Graceful drain: stop admissions (submits raise a typed
        ServeError), then wait up to *timeout* seconds (default the
        ``MXNET_SERVE_DRAIN_TIMEOUT`` knob) for every accepted request
        — queued and in-flight — to resolve.  Returns True when the
        queue fully drained, False on timeout (accepted work may still
        be in flight).  Idempotent."""
        if timeout is None:
            from ..config import get_env
            timeout = get_env("MXNET_SERVE_DRAIN_TIMEOUT")
        deadline = _time.monotonic() + max(0.0, float(timeout))
        with self._cond:
            self._draining = True
            # the drain's machine-readable record: how many accepted
            # requests it had to wait on, and whether it timed out —
            # rolling deploys gate on "zero abandoned work" from this
            # instead of inferring it from counters
            waited = self._accepted_locked()
            self._cond.notify_all()
            timed_out = False
            while self._pending or self._inflight:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    timed_out = True
                    break
                self._cond.wait(timeout=remaining)
            self._last_drain_stats = {"waited_requests": waited,
                                      "timed_out": timed_out}
        return not timed_out

    def undrain(self):
        """Resume admissions after a drain (an aborted rolling deploy
        must hand the replica back to service, not leave it shedding
        forever).  No-op on a closed or unhealthy batcher.  Returns
        True when admissions are open again."""
        with self._cond:
            if self._stopped or self._unhealthy:
                return False
            self._draining = False
            self._cond.notify_all()
        return True

    def flush(self, timeout=None):
        """Wait (bounded) for every request ALREADY accepted to
        resolve, without stopping admissions — the alias-cutover
        primitive: after repointing traffic, flush the old target so
        the requests it accepted are never dropped by a follow-up
        teardown.  Returns True when they all resolved in time."""
        if timeout is None:
            from ..config import get_env
            timeout = get_env("MXNET_SERVE_DRAIN_TIMEOUT")
        deadline = _time.monotonic() + max(0.0, float(timeout))
        with self._lock:
            # everything accepted up to now dispatches without waiting
            # out its coalescing window — flush means "land it"
            self._flush_horizon = max(self._flush_horizon,
                                      _time.monotonic())
            futs = [r.future for r in self._pending if not r.cancelled]
            futs.extend(r.future for r in self._inflight)
            self._cond.notify_all()
        for fut in futs:
            remaining = deadline - _time.monotonic()
            if remaining <= 0 or not fut._event.wait(remaining):
                return False
        return True

    def close(self, timeout=5.0):
        """Stop the dispatcher.  Queued-but-undispatched requests fail
        with a :class:`ServeError`; the in-flight batch (if any)
        completes.  A dispatcher that cannot be joined within
        *timeout* (wedged in a dispatch) is surfaced: ``closed_dirty``
        turns True, the dirty-close counter bumps and a structured
        warning event records it — close never lies about being
        clean.  Returns True on a clean close."""
        with self._lock:
            if self._stopped:
                return not self._closed_dirty
            self._stopped = True
            orphans = [r for r in self._pending if not r.cancelled]
            for r in orphans:
                r.taken = True      # cancel() races the resolve below
            self._pending.clear()
            self._rows_pending = 0
            self._bytes_pending = 0
            if orphans:
                _QUEUE_DEPTH.dec(len(orphans))
            self._cond.notify_all()
            thread = self._thread
        for req in orphans:
            req.future._resolve(
                exc=ServeError("batcher %r closed before dispatch"
                               % self.name))
        thread.join(timeout)
        if thread.is_alive():
            with self._lock:
                self._closed_dirty = True
            _DIRTY_CLOSES_TOTAL.inc()
            _obs_events.emit(
                "warning", source="serve.batcher", kind="dirty_close",
                model=self.name,
                detail="dispatcher thread still alive %.1fs after "
                       "close — wedged dispatch" % timeout)
            log.warning(
                "serve batcher %r: close could not join the dispatcher "
                "within %.1fs (closed_dirty; the thread is daemonic and "
                "will not block exit)", self.name, timeout)
            return False
        return True
