"""Router — health-aware request spreading over a replica fleet.

The router is the fleet's front door: it holds one
:class:`ReplicaHandle` per replica process, spreads predicts across
the ready ones (round-robin), and survives any one of them dying:

* **Retry-with-failover** — a transport failure (connect refused,
  connection torn mid-reply, RPC timeout, partition) retries the SAME
  ``(client, seq, incarnation)`` request id on the next eligible
  replica; the id only ever re-lands on an already-tried replica when
  no fresh one is left, where the replica's idempotency window
  answers from cache instead of re-dispatching (the PR-7 kvstore
  discipline applied to serving).  Typed replica answers — shed,
  deadline-expired, serve errors — are answers, not failures: they
  re-raise immediately and never fail over.
* **Circuit breaker per replica** — ``MXNET_SERVE_BREAKER_FAILURES``
  consecutive transport failures open the breaker (no requests
  routed); after ``MXNET_SERVE_BREAKER_COOLDOWN`` one half-open
  trial goes through — success closes, failure re-opens.
* **Heartbeat-staleness ejection** — a probe thread HEALTH-polls
  every replica (``MXNET_SERVE_FLEET_HEARTBEAT``); a replica whose
  last successful probe is staler than ``MXNET_SERVE_EJECT_TIMEOUT``
  is ejected from the rotation (breaker forced open), and the next
  successful probe rejoins it.  Probes also carry the replica's own
  health surface (PR 10): draining or not-ready replicas are shed
  from routing before they ever see the request.
* **Hedging** (``MXNET_SERVE_HEDGE_MS``, off by default) — after the
  hedge delay a still-unanswered predict is re-issued to a second
  replica; the first typed answer wins and the loser is cancelled
  through the idempotency window, so a hedged request is dispatched
  at most once per replica and never double-answered.

The router-side chaos choke point (``fleet_partition_at``) sits
right before every frame goes out, so ci/fleet_chaos_drill.py drives
the exact failover/eject/rejoin code a real partition exercises.
"""

from __future__ import annotations

import os
import socket
import time as _time

import numpy as _np

from .buckets import RequestCancelled, ServeError
from .decode import DecodeJournal, _FAILOVERS_TOTAL, _RESUMED_TOTAL
from .replica import (MSG_CANCEL, MSG_DECODE_CANCEL, MSG_DECODE_CLOSE,
                      MSG_DECODE_NEXT, MSG_DECODE_OPEN, MSG_HEALTH,
                      MSG_PREDICT, MSG_REPLY, ReplicaServer,
                      error_class)
from .. import sanitizer as _san
from ..observability import events as _obs_events
from ..observability import metrics as _obs_metrics
from ..resilience import servechaos as _servechaos
from ..resilience.retry import backoff_delays

__all__ = ["CircuitBreaker", "DecodeStream", "ReplicaHandle",
           "Router"]

_REPLICAS_READY = _obs_metrics.gauge(
    "fleet_replicas_ready",
    "replicas currently routable (probed ready, breaker closed, not "
    "draining/ejected) — set by the router's probe loop")
_FAILED_OVER = _obs_metrics.counter(
    "fleet_requests_failed_over_total",
    "requests retried on another replica after a transport failure "
    "(connection death, torn frame, RPC timeout, partition)")
_HEDGED = _obs_metrics.counter(
    "fleet_requests_hedged_total",
    "requests re-issued to a second replica after the hedge delay "
    "(MXNET_SERVE_HEDGE_MS) passed unanswered")
_EJECTIONS = _obs_metrics.counter(
    "fleet_replica_ejections_total",
    "replicas ejected from the rotation on heartbeat staleness")
_ROUTER_REQUESTS = _obs_metrics.counter(
    "fleet_router_requests_total",
    "predicts accepted by the fleet router")

# how long a single connect attempt may retry before the router
# treats the replica as dead-at-connect and fails over (failover
# latency floor, not a correctness knob)
_CONNECT_BUDGET_S = 1.0


class CircuitBreaker:
    """Per-replica transport circuit breaker.

    closed --N consecutive failures--> open --cooldown--> half_open
    half_open: exactly ONE trial request goes through; success closes
    the breaker, failure re-opens it for another cooldown.  All
    timing on an injectable monotonic clock (tests)."""

    def __init__(self, failures=None, cooldown=None, clock=None,
                 label="breaker"):
        from ..config import get_env
        self._threshold = int(failures) if failures is not None \
            else get_env("MXNET_SERVE_BREAKER_FAILURES")
        self._cooldown = float(cooldown) if cooldown is not None \
            else get_env("MXNET_SERVE_BREAKER_COOLDOWN")
        self._clock = clock or _time.monotonic
        self._lock = _san.lock(label="serve.%s" % label)
        self._state = "closed"
        self._consecutive = 0
        self._opened_at = None
        self._trial_inflight = False

    @property
    def state(self):
        with self._lock:
            if self._state == "open" and \
                    self._clock() - self._opened_at >= self._cooldown:
                return "half_open"
            return self._state

    def allow(self):
        """May a request be dispatched now?  In half-open, only one
        trial holder gets True until it reports back."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open" and \
                    self._clock() - self._opened_at >= self._cooldown:
                self._state = "half_open"
                self._trial_inflight = False
            if self._state == "half_open" and not self._trial_inflight:
                self._trial_inflight = True
                return True
            return False

    def record_success(self):
        with self._lock:
            self._state = "closed"
            self._consecutive = 0
            self._trial_inflight = False

    def record_failure(self):
        with self._lock:
            self._consecutive += 1
            was_half_open = self._state == "half_open"
            self._trial_inflight = False
            if was_half_open or self._consecutive >= self._threshold:
                self._state = "open"
                self._opened_at = self._clock()

    def force_open(self):
        """Ejection: open regardless of the failure count (the
        cooldown still applies before a half-open trial)."""
        with self._lock:
            self._state = "open"
            self._opened_at = self._clock()
            self._trial_inflight = False


class ReplicaHandle:
    """Router-side view of one replica: address, connection pool,
    breaker, and the probe-loop's last health observation."""

    def __init__(self, host, port, http_port=0, key=None,
                 breaker=None):
        self.host = host
        self.port = int(port)
        self.http_port = int(http_port or 0)
        self.key = key or ("%s:%d" % (host, self.port))
        self.breaker = breaker or CircuitBreaker(
            label="breaker.%s" % self.key)
        self._lock = _san.lock(label="serve.replica_handle.%s"
                               % self.key)
        self._pool = []             # idle connected sockets
        self._draining = False      # router-side deploy mark
        self._ejected = False
        self._live = True
        self._replica_draining = False
        self._model_ready = None    # {model: bool} from the last probe
        self._last_ok = _time.monotonic()   # last successful probe/call
        _san.track(self, ("_pool", "_draining", "_ejected", "_live",
                          "_replica_draining", "_model_ready",
                          "_last_ok"),
                   label="serve.replica_handle.%s" % self.key)

    # -- probe-state accessors ---------------------------------------------
    @property
    def draining(self):
        with self._lock:
            return self._draining or self._replica_draining

    @property
    def ejected(self):
        with self._lock:
            return self._ejected

    def set_draining(self, flag):
        """Router/fleet-side deploy mark: stop routing NEW requests
        here (the replica keeps finishing what it accepted)."""
        with self._lock:
            self._draining = bool(flag)

    def last_ok_age(self):
        with self._lock:
            return _time.monotonic() - self._last_ok

    def note_ok(self):
        with self._lock:
            self._last_ok = _time.monotonic()

    def note_probe(self, rmeta):
        with self._lock:
            self._last_ok = _time.monotonic()
            self._live = bool(rmeta.get("live", True))
            self._replica_draining = bool(rmeta.get("draining"))
            models = rmeta.get("models") or {}
            self._model_ready = {n: bool(m.get("ready"))
                                 for n, m in models.items()}

    def note_ejected(self, flag):
        with self._lock:
            self._ejected = bool(flag)

    def eligible(self, model=None):
        """Routable for *model* right now?  (The breaker's half-open
        trial admission happens at dispatch time, not here.)"""
        with self._lock:
            if (self._draining or self._replica_draining
                    or self._ejected or not self._live):
                return False
            ready = self._model_ready
        if self.breaker.state == "open":
            return False
        if model is not None and ready is not None:
            # optimistic before the first probe lands (ready is None)
            return ready.get(model, False)
        return True

    # -- connection pool ---------------------------------------------------
    def acquire(self, timeout):
        with self._lock:
            sock = self._pool.pop() if self._pool else None
        if sock is None:
            # ONE bounded connect attempt: a black-holed replica must
            # cost _CONNECT_BUDGET_S before failover, not the kernel
            # SYN timeout (~2 min), and a refused connect fails over
            # immediately — the next probe round is the retry
            sock = socket.create_connection(
                (self.host, self.port), timeout=_CONNECT_BUDGET_S)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(timeout if timeout else None)
        return sock

    def release(self, sock):
        with self._lock:
            if len(self._pool) < 8:
                self._pool.append(sock)
                return
        try:
            sock.close()
        except OSError:
            pass

    def close_pool(self):
        with self._lock:
            pool, self._pool = self._pool, []
        for s in pool:
            try:
                s.close()
            except OSError:
                pass


class Router:
    """Spread predicts across replicas; survive any one dying.

    Parameters
    ----------
    replicas : iterable, optional
        ``(host, port)`` / ``(host, port, http_port)`` tuples or
        :class:`ReplicaHandle` instances.
    hedge_ms, rpc_timeout, retries, probe_interval, eject_timeout :
        Override the corresponding ``MXNET_SERVE_*`` knobs.
    probe : bool
        Start the health-probe thread (default True; unit tests that
        script probe state pass False).
    """

    def __init__(self, replicas=(), hedge_ms=None, rpc_timeout=None,
                 retries=None, probe_interval=None, eject_timeout=None,
                 probe=True, client_id=None):
        from ..config import get_env
        self._hedge = (float(hedge_ms)
                       if hedge_ms is not None
                       else get_env("MXNET_SERVE_HEDGE_MS")) / 1e3
        self._rpc_timeout = float(rpc_timeout) if rpc_timeout is not None \
            else get_env("MXNET_SERVE_RPC_TIMEOUT")
        self._retries = max(1, int(retries) if retries is not None
                            else get_env("MXNET_SERVE_ROUTER_RETRIES"))
        self._probe_interval = float(probe_interval) \
            if probe_interval is not None \
            else get_env("MXNET_SERVE_FLEET_HEARTBEAT")
        self._eject_timeout = float(eject_timeout) \
            if eject_timeout is not None \
            else get_env("MXNET_SERVE_EJECT_TIMEOUT")
        self.client_id = client_id or ("router-%d-%d"
                                       % (os.getpid(), id(self) & 0xFFFF))
        # wall-clock incarnation TOKEN (not a deadline): a restarted
        # router with the same client id must not be deduped against
        # its previous life — same rule as the kvstore's epoch token
        self.incarnation = int(_time.time() * 1000) & 0x7FFFFFFF
        self._lock = _san.lock(label="serve.router")
        self._replicas = {}     # key -> ReplicaHandle
        self._seq = 0
        self._rr = 0
        # router-side half of the decode journal contract: identity,
        # prompt and accepted-token log per fleet streaming session —
        # the resume payload when a replica dies or drains mid-stream
        self._decode_journal = DecodeJournal(
            "router.%s" % self.client_id)
        self._stop = _san.event()
        _san.track(self, ("_replicas", "_seq", "_rr"),
                   label="serve.router")
        for r in replicas:
            self.add_replica(r)
        self._probe_thread = None
        if probe:
            self._probe_thread = _san.thread(
                target=self._probe_loop, name="serve-router-probe",
                daemon=True)
            self._probe_thread.start()

    # -- membership --------------------------------------------------------
    def add_replica(self, replica):
        """Register a replica: a ``ReplicaHandle`` or a
        ``(host, port[, http_port])`` tuple.  Returns the handle."""
        if not isinstance(replica, ReplicaHandle):
            replica = ReplicaHandle(*replica)
        with self._lock:
            self._replicas[replica.key] = replica
        _obs_events.emit("fleet", kind="replica_admit",
                         replica=replica.key)
        return replica

    def remove_replica(self, key):
        with self._lock:
            handle = self._replicas.pop(key, None)
        if handle is not None:
            handle.close_pool()
            _obs_events.emit("fleet", kind="replica_remove",
                             replica=key)
        return handle

    def replicas(self):
        with self._lock:
            return dict(self._replicas)

    def handle(self, key):
        with self._lock:
            h = self._replicas.get(key)
        if h is None:
            raise ServeError("router knows no replica %r (have %s)"
                             % (key, sorted(self.replicas())))
        return h

    def set_draining(self, key, flag=True):
        """Deploy mark: stop routing NEW requests to *key* (accepted
        work keeps flowing back)."""
        self.handle(key).set_draining(flag)

    def ready_count(self, model=None):
        return sum(1 for h in self.replicas().values()
                   if h.eligible(model))

    # -- transport ---------------------------------------------------------
    def _call(self, handle, kind, meta=None, tensors=(), timeout=None):
        """One RPC round trip on *handle* (pooled connection).  EVERY
        transport problem — connect failure (acquire is inside the
        try: an ETIMEDOUT/EHOSTUNREACH/EMFILE here must take the
        failover path, not escape raw and strand a half-open
        breaker's trial), torn frame, RPC timeout, the injected
        partition — closes the socket and surfaces as
        ``ConnectionError``; the reply (ok or typed err) comes back
        as ``(meta, tensors)``."""
        from .._kvstore_impl import _recv_frame, _send_frame
        _servechaos.on_router_send(handle.key, port=handle.port)
        timeout = self._rpc_timeout if timeout is None else timeout
        sock = None
        try:
            sock = handle.acquire(timeout)
            _send_frame(sock, kind, meta or {}, tensors)
            rkind, rmeta, rtensors = _recv_frame(sock)
        except (ConnectionError, OSError, ValueError) as exc:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            raise ConnectionError(
                "replica %s: transport failure (%s: %s)"
                % (handle.key, type(exc).__name__, exc)) from exc
        if rkind != MSG_REPLY:
            try:
                sock.close()
            except OSError:
                pass
            raise ConnectionError(
                "replica %s: protocol desync (reply kind %d)"
                % (handle.key, rkind))
        # the reply tensors VIEW the frame buffer — copy before the
        # socket (and buffer) go back to the pool
        rtensors = [_np.array(t) for t in rtensors]
        handle.release(sock)
        handle.note_ok()
        return rmeta, rtensors

    def control(self, key, kind, meta=None, tensors=(), timeout=None):
        """Raw control-plane RPC to one replica (LOAD / DRAIN / STATS
        / STOP ... — the fleet's deploy primitive).  Raises the typed
        serve error for an ``err`` reply."""
        rmeta, rtensors = self._call(self.handle(key), kind, meta,
                                     tensors, timeout)
        if rmeta.get("status") != "ok":
            raise error_class(rmeta.get("code"))(
                "replica %s: %s" % (key, rmeta.get("msg")))
        return rmeta, rtensors

    # -- request routing ---------------------------------------------------
    def _serialize(self, data):
        if isinstance(data, dict):
            names = sorted(data)
            return names, [_np.asarray(data[n]) for n in names]
        return [], [_np.asarray(data)]

    def _candidates(self, model):
        with self._lock:
            handles = list(self._replicas.values())
            start = self._rr
            self._rr += 1
        if not handles:
            return []
        order = [handles[(start + i) % len(handles)]
                 for i in range(len(handles))]
        return [h for h in order if h.eligible(model)]

    @staticmethod
    def _interpret(rmeta, rtensors):
        if rmeta.get("status") == "ok":
            return rtensors
        raise error_class(rmeta.get("code"))(rmeta.get("msg") or
                                             "replica error")

    def predict(self, model, data, deadline_ms=None):
        """Route one predict.  *data*: {input: array} or a bare array
        for single-input models.  Returns the outputs as a list of
        host numpy arrays; raises the same typed errors the
        single-process serve path does.  Transport failures fail over
        (same request id); typed replica answers do not."""
        names, tensors = self._serialize(data)
        with self._lock:
            self._seq += 1
            seq = self._seq
        meta = {"model": model, "inputs": names,
                "req": [self.client_id, seq, self.incarnation]}
        if deadline_ms is not None:
            meta["deadline_ms"] = float(deadline_ms)
        _ROUTER_REQUESTS.inc()
        candidates = self._candidates(model)
        if not candidates:
            raise ServeError(
                "no replica is routable for model %r (replicas: %s)"
                % (model, sorted(self.replicas())))
        if self._hedge > 0 and len(candidates) >= 2:
            return self._hedged_predict(model, meta, tensors,
                                        candidates)
        return self._failover_predict(model, meta, tensors,
                                      candidates)

    # typed shed codes that are safe to reroute: the replica answered
    # WITHOUT dispatching the request (admission-time shed), so trying
    # another replica cannot double-dispatch it
    _REROUTE_CODES = frozenset(("draining", "overload"))

    def _failover_predict(self, model, meta, tensors, candidates):
        errors = []
        tried = []      # replicas that failed in TRANSPORT
        last_shed = None
        attempts = 0
        # one pass over the fresh candidates, then — if the attempt
        # budget allows — ONE wrap-around pass over the transport-
        # failed ones: the same request id re-lands there, and the
        # replica's dedup window answers from cache if the first
        # attempt actually landed (never re-dispatches)
        plan = list(candidates)
        idx = 0
        wrapped = False
        while attempts < self._retries:
            if idx >= len(plan):
                if wrapped or not tried:
                    break
                plan = list(tried)
                idx = 0
                wrapped = True
            handle = plan[idx]
            idx += 1
            if not handle.breaker.allow():
                continue
            attempts += 1
            if tried:
                _FAILED_OVER.inc()
                _obs_events.emit("fleet", kind="failover", model=model,
                                 req=meta["req"], to=handle.key,
                                 attempt=attempts)
            try:
                rmeta, rtensors = self._call(handle, MSG_PREDICT, meta,
                                             tensors)
            except ConnectionError as exc:
                handle.breaker.record_failure()
                if handle not in tried:
                    tried.append(handle)
                errors.append("%s: %s" % (handle.key, exc))
                continue
            handle.breaker.record_success()
            if rmeta.get("status") != "ok" and \
                    rmeta.get("code") in self._REROUTE_CODES:
                # admission-time shed (deploy drain, overload): the
                # request never dispatched there — reroute, and only
                # surface the typed shed if every replica sheds.
                # Deliberately NOT in `tried`: a wrap-around retry of
                # a shed makes no progress.
                last_shed = (rmeta, rtensors)
                errors.append("%s: shed (%s)" % (handle.key,
                                                 rmeta.get("code")))
                _obs_events.emit("fleet", kind="reroute_shed",
                                 model=model, req=meta["req"],
                                 replica=handle.key,
                                 code=rmeta.get("code"))
                continue
            return self._interpret(rmeta, rtensors)
        if last_shed is not None:
            return self._interpret(*last_shed)      # raises typed
        raise ServeError(
            "request %s failed on every routable replica (%d attempts"
            "): %s" % (meta["req"], attempts,
                       "; ".join(errors) or "no replica admitted it"))

    # -- hedging -----------------------------------------------------------
    def _hedged_predict(self, model, meta, tensors, candidates):
        """Primary dispatch + a hedge to a SECOND replica if the
        primary is still unanswered after the hedge delay.  First
        typed answer wins; the loser is cancelled through the
        idempotency window.  Each replica sees the request at most
        once (distinct candidates; transport failures fall back to
        the sequential failover path over the untried rest)."""
        lock = _san.lock(label="serve.router.hedge")
        cond = _san.condition(lock, label="serve.router.hedge")
        results = []    # ("answer"|"shed"|"transport", handle, payload)

        def attempt(handle):
            try:
                payload = self._call(handle, MSG_PREDICT, meta, tensors)
                handle.breaker.record_success()
                rmeta = payload[0]
                if rmeta.get("status") != "ok" and \
                        rmeta.get("code") in self._REROUTE_CODES:
                    entry = ("shed", handle, payload)
                else:
                    entry = ("answer", handle, payload)
            except ConnectionError as exc:
                handle.breaker.record_failure()
                entry = ("transport", handle, exc)
            with lock:
                results.append(entry)
                cond.notify_all()

        # the primary dispatch honors the breaker like the failover
        # path does — a half-open replica gets its ONE trial, not a
        # burst of concurrent hedged primaries
        primary = next((h for h in candidates if h.breaker.allow()),
                       None)
        if primary is None:
            return self._failover_predict(model, meta, tensors,
                                          candidates)
        launched = [primary]
        _san.thread(target=attempt, args=(primary,),
                    daemon=True).start()
        deadline = _time.monotonic() + (self._rpc_timeout or 60.0)
        hedge_by = _time.monotonic() + self._hedge
        hedged = False
        while True:
            with lock:
                answer = next((r for r in results if r[0] == "answer"),
                              None)
                failed = len(results)
            if answer is not None:
                break
            if failed >= len(launched):
                # every launched attempt died in transport or shed:
                # hand the plain failover path the never-launched
                # candidates FIRST, then the transport-failed launched
                # ones — its wrap-around retries them with the same
                # id, where the dedup window answers from cache (the
                # retry budget the non-hedged path would have given
                # them)
                with lock:
                    transport_failed = [r[1] for r in results
                                        if r[0] == "transport"]
                rest = [h for h in candidates if h not in launched] \
                    + transport_failed
                if rest:
                    return self._failover_predict(model, meta, tensors,
                                                  rest)
                with lock:
                    shed = next((r for r in results if r[0] == "shed"),
                                None)
                if shed is not None:
                    return self._interpret(*shed[2])    # raises typed
                raise ServeError(
                    "hedged request %s failed on every replica: %s"
                    % (meta["req"],
                       "; ".join("%s: %s" % (r[1].key, r[2])
                                 for r in results)))
            now = _time.monotonic()
            if now >= deadline:
                raise ServeError(
                    "hedged request %s unanswered after %.1fs"
                    % (meta["req"], self._rpc_timeout))
            if not hedged and now >= hedge_by:
                second = next((h for h in candidates
                               if h not in launched
                               and h.breaker.allow()), None)
                if second is not None:
                    hedged = True
                    launched.append(second)
                    _HEDGED.inc()
                    _obs_events.emit("fleet", kind="hedge",
                                     model=model, req=meta["req"],
                                     to=second.key)
                    _san.thread(target=attempt, args=(second,),
                                daemon=True).start()
                else:
                    hedge_by = deadline     # nobody to hedge to
            with lock:
                if not any(r[0] == "answer" for r in results) \
                        and len(results) < len(launched):
                    cond.wait(timeout=min(
                        0.05,
                        max(0.001, (hedge_by if not hedged
                                    else deadline)
                            - _time.monotonic())))
        winner_handle = answer[1]
        losers = [h for h in launched if h is not winner_handle]
        for loser in losers:
            # best-effort: reclaim the loser's queue slot and pin the
            # id cancelled in its window so the hedged id can never be
            # answered twice or re-dispatched there
            _san.thread(target=self._cancel_on, args=(loser, meta),
                        daemon=True).start()
        return self._interpret(*answer[2])

    def _cancel_on(self, handle, meta):
        try:
            self._call(handle, MSG_CANCEL, {"req": meta["req"]},
                       timeout=min(5.0, self._rpc_timeout or 5.0))
        except (ConnectionError, OSError):
            pass

    # -- streaming decode --------------------------------------------------
    @property
    def decode_journal(self):
        """The router-side session journal (resume source of truth
        for fleet streaming sessions)."""
        return self._decode_journal

    def decode_open(self, model, prompt, max_new_tokens=None,
                    deadline_ms=None):
        """Open one fleet streaming decode session on an eligible
        replica.  Returns a :class:`DecodeStream` — the stable handle
        the caller keeps across replica death and deploys: tokens are
        journaled as they stream back, and a dead/draining replica's
        session transparently re-opens on a successor from the
        journal, resuming bit-equal.  Raises the typed serve errors
        (``KVPoolExhausted``/``OverloadError`` when no replica can
        hold the session, ``ServeError`` when none is routable)."""
        with self._lock:
            self._seq += 1
            seq = self._seq
        stream = DecodeStream(self, model, prompt, seq,
                              max_new_tokens=max_new_tokens,
                              deadline_ms=deadline_ms)
        stream._open_somewhere("open")
        return stream

    # -- health probing ----------------------------------------------------
    def _probe_loop(self):
        while not self._stop.wait(self._probe_interval):
            try:
                self.probe_once()
            except Exception:   # the fleet's health surface must
                log.exception("router probe round failed")  # survive

    def probe_once(self):
        """One probe round over every replica: refresh health state,
        eject on staleness, rejoin on recovery, refresh the
        fleet_replicas_ready gauge.  Called by the probe thread; unit
        tests call it directly."""
        for handle in self.replicas().values():
            try:
                rmeta, _ = self._call(
                    handle, MSG_HEALTH, {},
                    timeout=max(1.0, self._probe_interval * 4))
            except ConnectionError:
                if not handle.ejected and \
                        handle.last_ok_age() > self._eject_timeout:
                    handle.note_ejected(True)
                    handle.breaker.force_open()
                    _EJECTIONS.inc()
                    _obs_events.emit("fleet", kind="eject",
                                     replica=handle.key,
                                     stale_s=round(
                                         handle.last_ok_age(), 3))
                continue
            handle.note_probe(rmeta)
            if handle.ejected:
                handle.note_ejected(False)
                handle.breaker.record_success()
                _obs_events.emit("fleet", kind="rejoin",
                                 replica=handle.key)
        _REPLICAS_READY.set(self.ready_count())

    def close(self):
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5.0)
        for handle in self.replicas().values():
            handle.close_pool()


class DecodeStream:
    """One fleet streaming decode session under a stable handle.

    The router places the session on an eligible replica
    (DECODE_OPEN) and the caller pulls tokens with
    :meth:`next_output` (DECODE_NEXT per index — answered from the
    replica's retained stream, so a retried index dedups instead of
    re-decoding).  Every accepted token is journaled router-side; when
    the serving replica dies (transport failure) or drains (deploy
    migration), the session re-opens on a successor with the journal
    as the resume payload — the successor re-prefills and replays the
    log bit-checked, and the caller keeps reading under the SAME
    handle.  Resume attempts ride the shared jittered backoff,
    bounded by the router's retry budget per failover; past the
    budget the stream fails typed.  A cancelled stream is NEVER
    resumed — a cancel racing a failover wins."""

    def __init__(self, router, model, prompt, seq,
                 max_new_tokens=None, deadline_ms=None):
        import random
        self._router = router
        self.model = model
        self.client = router.client_id
        self.seq = int(seq)
        self.incarnation = 0
        names, tensors = router._serialize(prompt)
        self._prompt_names = names
        self._prompt_tensors = tensors
        self.max_new_tokens = max_new_tokens
        self._deadline_ms = deadline_ms
        self._rng = random.Random()
        self._lock = _san.lock(label="serve.decode.stream.%d" % seq)
        self._handle = None         # current ReplicaHandle
        self._base = 0              # successor-side resume offset
        self._ntokens = 0
        self._out_names = None      # leaf names of one output tree
        self._done = False
        self.finish_reason = None
        self._error = None
        self._cancelled = False
        self.failover_count = 0
        self.resume_stamps = []     # (t_detect, t_resumed) monotonic
        length = int(tensors[0].shape[0]) if tensors else 0
        router._decode_journal.open(
            self.client, self.seq, 0,
            prompt=dict(zip(names, tensors)) if names else tensors[0],
            length=length, max_new_tokens=max_new_tokens)

    @property
    def key(self):
        return (self.client, self.seq)

    @property
    def replica(self):
        """The key of the replica currently serving this stream."""
        with self._lock:
            return self._handle.key if self._handle is not None \
                else None

    def tokens(self):
        """Every accepted token so far (the journal log — survives
        failovers, readable after a typed failure)."""
        return self._router._decode_journal.tokens(self.key)

    def done(self):
        with self._lock:
            return self._done

    @property
    def error(self):
        with self._lock:
            return self._error

    # -- placement / failover ----------------------------------------------
    def _open_meta(self, resume_tokens):
        meta = {"model": self.model,
                "session": [self.client, self.seq, self.incarnation],
                "inputs": self._prompt_names,
                "resume": len(resume_tokens),
                "out_names": self._out_names}
        if self.max_new_tokens is not None:
            meta["max_new_tokens"] = self.max_new_tokens
        if self._deadline_ms is not None:
            meta["deadline_ms"] = float(self._deadline_ms)
        tensors = list(self._prompt_tensors)
        for tok in resume_tokens:
            _, leaves = ReplicaServer._out_wire(tok)
            tensors.extend(leaves)
        return meta, tensors

    def _open_somewhere(self, why, failed=None):
        """Place (or re-place) the session on an eligible replica —
        DECODE_OPEN with the journal as the resume payload.  Typed
        sheds (draining/overload/rebuilding) reroute; transport
        failures back off on the shared jittered schedule; the
        router's retry budget bounds the attempts."""
        router = self._router
        resume_tokens = self.tokens()
        meta, tensors = self._open_meta(resume_tokens)
        delays = backoff_delays(router._retries + 1, 0.05, 1.0, 2.0,
                                0.5, self._rng)
        errors = []
        last_shed = None
        attempts = 0
        while attempts < router._retries:
            if self._cancelled:
                raise RequestCancelled(
                    "decode session (%s, %d) cancelled — a cancelled "
                    "session is never resumed"
                    % (self.client, self.seq))
            candidates = [h for h in router._candidates(self.model)
                          if h is not failed] \
                or router._candidates(self.model)
            handle = next((h for h in candidates
                           if h.breaker.allow()), None)
            if handle is None:
                errors.append("no routable replica")
                attempts += 1
                _time.sleep(next(delays))
                continue
            attempts += 1
            try:
                rmeta, _ = router._call(handle, MSG_DECODE_OPEN, meta,
                                        tensors)
            except ConnectionError as exc:
                handle.breaker.record_failure()
                failed = handle
                errors.append("%s: %s" % (handle.key, exc))
                _time.sleep(next(delays))
                continue
            handle.breaker.record_success()
            if rmeta.get("status") != "ok":
                code = rmeta.get("code")
                if code in Router._REROUTE_CODES:
                    # admission-time shed: never dispatched there
                    last_shed = rmeta
                    failed = handle
                    errors.append("%s: shed (%s)" % (handle.key, code))
                    _time.sleep(next(delays))
                    continue
                raise error_class(code)(rmeta.get("msg")
                                        or "replica error")
            with self._lock:
                self._handle = handle
                self._base = int(rmeta.get("base", 0))
            _obs_events.emit(
                "decode",
                kind="migrate" if why == "migrate" else "resume"
                if why != "open" else "session_place",
                model=self.model, client=str(self.client),
                session_seq=self.seq, incarnation=self.incarnation,
                to=handle.key, tokens=len(resume_tokens), why=why)
            return
        if last_shed is not None:
            raise error_class(last_shed.get("code"))(
                last_shed.get("msg") or "replica shed")
        raise ServeError(
            "decode session (%s, %d): %s budget exhausted after %d "
            "attempt(s): %s"
            % (self.client, self.seq,
               "open" if why == "open" else "resume", attempts,
               "; ".join(errors) or "no replica admitted it"))

    def _failover(self, why, exc=None):
        """The serving replica died or drained mid-stream: bump the
        incarnation and re-open on a successor from the journal —
        transparent to the caller, bit-equal to an uninterrupted
        stream (the successor replays the log bit-checked)."""
        with self._lock:
            if self._cancelled:
                raise RequestCancelled(
                    "decode session (%s, %d) cancelled during "
                    "failover — never resumed"
                    % (self.client, self.seq))
            failed = self._handle
            self._handle = None
            self.incarnation += 1
            self.failover_count += 1
        t0 = _time.monotonic()
        _FAILOVERS_TOTAL.inc()
        _obs_events.emit("decode", kind="failover", model=self.model,
                         client=str(self.client), session_seq=self.seq,
                         incarnation=self.incarnation,
                         from_=failed.key if failed else None,
                         why=why,
                         error=str(exc)[:200] if exc else None)
        try:
            self._open_somewhere(why, failed=failed)
        except Exception as oexc:
            with self._lock:
                self._done = True
                self._error = oexc
                self.finish_reason = "failover_exhausted"
            self._router._decode_journal.close(
                self.key, "failover_exhausted")
            raise
        self.resume_stamps.append((t0, _time.monotonic()))
        _RESUMED_TOTAL.inc()

    # -- token stream ------------------------------------------------------
    def next_output(self, timeout=None):
        """The next accepted token (host tree).  Blocks across
        failovers; raises ``StopIteration`` on a clean finish, the
        typed error on failure, ``TimeoutError`` past *timeout*."""
        deadline = None if timeout is None \
            else _time.monotonic() + timeout
        with self._lock:
            if self._error is not None:
                raise self._error
            if self._cancelled:
                raise RequestCancelled(
                    "decode session (%s, %d) cancelled"
                    % (self.client, self.seq))
            if self._done:
                raise StopIteration(
                    "decode session (%s, %d) finished (%s)"
                    % (self.client, self.seq, self.finish_reason))
            index = self._ntokens
            handle = self._handle
        while True:
            if deadline is not None and \
                    _time.monotonic() >= deadline:
                raise TimeoutError(
                    "decode session (%s, %d): token %d not available "
                    "after %ss" % (self.client, self.seq, index,
                                   timeout))
            if handle is None:
                self._failover("resume")
                with self._lock:
                    handle = self._handle
            wait_s = 5.0
            if deadline is not None:
                wait_s = max(0.05, min(
                    wait_s, deadline - _time.monotonic()))
            try:
                rmeta, rtensors = self._router._call(
                    handle, MSG_DECODE_NEXT,
                    {"session": [self.client, self.seq,
                                 self.incarnation],
                     "index": index, "wait_s": wait_s})
            except ConnectionError as exc:
                handle.breaker.record_failure()
                self._failover("resume", exc)
                with self._lock:
                    handle = self._handle
                continue
            handle.breaker.record_success()
            if rmeta.get("status") != "ok":
                code = rmeta.get("code")
                if code == "draining":
                    # deploy drain mid-stream: migrate to a successor
                    self._failover("migrate")
                    with self._lock:
                        handle = self._handle
                    continue
                err = error_class(code)(rmeta.get("msg")
                                        or "replica error")
                with self._lock:
                    self._done = True
                    self._error = err
                    self.finish_reason = code
                self._router._decode_journal.close(self.key, code)
                raise err
            if rmeta.get("pending"):
                continue        # bounded wait elapsed — poll again
            if rmeta.get("done"):
                with self._lock:
                    self._done = True
                    self.finish_reason = rmeta.get("reason")
                self._router._decode_journal.close(
                    self.key, rmeta.get("reason") or "finished")
                raise StopIteration(
                    "decode session (%s, %d) finished (%s)"
                    % (self.client, self.seq, self.finish_reason))
            names = rmeta.get("out_names")
            out = ReplicaServer._out_unwire(names, rtensors)
            self._router._decode_journal.append(self.key, index, out)
            with self._lock:
                self._out_names = names
                self._ntokens = index + 1
            return out

    def result(self, timeout=None):
        """Drain the stream to completion; returns the FULL accepted
        token list (journal log — pre-failover tokens included), or
        raises the typed failure."""
        deadline = None if timeout is None \
            else _time.monotonic() + timeout
        while True:
            remaining = None if deadline is None \
                else max(0.001, deadline - _time.monotonic())
            try:
                self.next_output(timeout=remaining)
            except StopIteration:
                return self.tokens()

    def cancel(self):
        """Abandon the stream.  The cancel is pinned on the serving
        replica (a late failover re-open answers ``cancelled``) and
        the session is never resumed."""
        with self._lock:
            if self._done:
                return False
            self._cancelled = True
            self._done = True
            self.finish_reason = "cancelled"
            self._error = RequestCancelled(
                "decode session (%s, %d) cancelled by its caller"
                % (self.client, self.seq))
            handle = self._handle
        self._router._decode_journal.close(self.key, "cancelled")
        if handle is not None:
            try:
                self._router._call(
                    handle, MSG_DECODE_CANCEL,
                    {"session": [self.client, self.seq,
                                 self.incarnation]},
                    timeout=min(5.0, self._router._rpc_timeout or 5.0))
            except (ConnectionError, OSError):
                pass
        return True

    def close(self):
        """Release the replica-side session record (best effort; a
        live stream is cancelled first)."""
        with self._lock:
            live = not self._done
            handle = self._handle
        if live:
            self.cancel()
            with self._lock:
                handle = self._handle
        if handle is not None:
            try:
                self._router._call(
                    handle, MSG_DECODE_CLOSE,
                    {"session": [self.client, self.seq,
                                 self.incarnation]},
                    timeout=min(5.0, self._router._rpc_timeout or 5.0))
            except (ConnectionError, OSError):
                pass
