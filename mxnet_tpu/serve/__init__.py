"""``mxnet_tpu.serve`` — the compiled inference subsystem.

Production serving for models built with this framework:

* :class:`BucketLadder` — the finite set of padded shapes a model may
  run at (buckets.py);
* :class:`CompiledPredictor` — one AOT-compiled XLA program per
  bucket, built with ``jit(...).lower().compile()`` at load time so
  no trace or compile ever happens in the request path, plus donated
  KV-cache decode sessions (predictor.py);
* :class:`DynamicBatcher` / :class:`ServeFuture` — continuous
  batching: many callers, one padded dispatch — with admission
  control (:class:`OverloadError`), per-request deadlines
  (:class:`DeadlineExceededError`), caller-side cancellation
  (:class:`RequestCancelled`), supervised dispatcher restarts and
  graceful drain (batcher.py);
* :class:`KVPool` / :class:`DecodeEngine` / :class:`DecodeBatcher` —
  continuously-batched LLM decode: a paged KV-cache pool (fixed
  device blocks, per-session block tables, typed
  :class:`KVPoolExhausted` shedding), AOT decode-tick programs per
  session-count rung + bucketed prefill programs, and the tick loop
  where sessions join/leave between ticks — one dispatch serves
  every session's next token (kvpool.py, decode.py;
  :class:`SpeculativeDecoder` is the opt-in draft/verify layer);
* :class:`ModelRegistry` — multi-model load/unload/alias with a warm
  program cache, drain-before-teardown (decode sessions included),
  and the ``health``/``ready``/``live`` probe surface backed by
  :class:`HealthBoard` (registry.py, health.py); :func:`c_registry`
  is the process-wide instance the C predict ABI routes through;
* :class:`ReplicaServer` / :class:`Router` / :class:`Fleet` — the
  multi-replica fleet: a replica process wraps a registry behind the
  kvstore wire framing with idempotent ``(client, seq, incarnation)``
  predicts and an HTTP probe endpoint; the router spreads load with
  retry-with-failover, per-replica circuit breakers
  (:class:`CircuitBreaker`), heartbeat-staleness ejection and opt-in
  request hedging; the fleet spawns/replaces replica processes
  (warming from the shared persistent XLA compile cache) and runs
  drain-aware rolling deploys that drop zero accepted requests
  (replica.py, router.py, fleet.py).

See docs/serving.md for the architecture, fault-tolerance semantics,
knobs and metrics catalog.
"""

from .buckets import (BucketLadder, DeadlineExceededError,  # noqa: F401
                      OverloadError, RequestCancelled, ServeError)
from .health import STATES, HealthBoard  # noqa: F401
from .kvpool import KVPool, KVPoolExhausted  # noqa: F401
from .predictor import CompiledPredictor, DecodeSession  # noqa: F401
from .batcher import DynamicBatcher, ServeFuture  # noqa: F401
from .decode import (DecodeBatcher, DecodeEngine,  # noqa: F401
                     DecodeJournal, PagedSession, SpeculativeDecoder)
from .registry import ModelRegistry, c_registry  # noqa: F401
from .replica import (ReplicaDraining, ReplicaServer,  # noqa: F401
                      start_http_probe)
from .router import (CircuitBreaker, DecodeStream,  # noqa: F401
                     ReplicaHandle, Router)
from .fleet import Fleet  # noqa: F401

__all__ = ["BucketLadder", "ServeError", "OverloadError",
           "DeadlineExceededError", "RequestCancelled",
           "CompiledPredictor", "DecodeSession", "DynamicBatcher",
           "ServeFuture", "ModelRegistry", "c_registry", "HealthBoard",
           "STATES", "KVPool", "KVPoolExhausted", "DecodeEngine",
           "DecodeBatcher", "DecodeJournal", "PagedSession",
           "SpeculativeDecoder", "ReplicaServer", "ReplicaDraining",
           "start_http_probe", "CircuitBreaker", "DecodeStream",
           "ReplicaHandle", "Router", "Fleet"]
