"""``mxnet_tpu.serve`` — the compiled inference subsystem.

Production serving for models built with this framework:

* :class:`BucketLadder` — the finite set of padded shapes a model may
  run at (buckets.py);
* :class:`CompiledPredictor` — one AOT-compiled XLA program per
  bucket, built with ``jit(...).lower().compile()`` at load time so
  no trace or compile ever happens in the request path, plus donated
  KV-cache decode sessions (predictor.py);
* :class:`DynamicBatcher` / :class:`ServeFuture` — continuous
  batching: many callers, one padded dispatch (batcher.py);
* :class:`ModelRegistry` — multi-model load/unload/alias with a warm
  program cache; :func:`c_registry` is the process-wide instance the
  C predict ABI routes through (registry.py).

See docs/serving.md for the architecture, knobs and metrics catalog.
"""

from .buckets import BucketLadder, ServeError  # noqa: F401
from .predictor import CompiledPredictor, DecodeSession  # noqa: F401
from .batcher import DynamicBatcher, ServeFuture  # noqa: F401
from .registry import ModelRegistry, c_registry  # noqa: F401

__all__ = ["BucketLadder", "ServeError", "CompiledPredictor",
           "DecodeSession", "DynamicBatcher", "ServeFuture",
           "ModelRegistry", "c_registry"]
