"""Fleet — N replica processes, one router, rolling deploys.

This turns the serve subsystem from a library into a deployable
system (ROADMAP item 4): the :class:`Fleet` spawns N
``python -m mxnet_tpu.serve.replica`` processes (each a full
ModelRegistry behind the socket RPC surface of replica.py), fronts
them with a :class:`~mxnet_tpu.serve.router.Router`, and owns the
operations a real fleet needs:

* **Spawn / replace** — replicas share one persistent XLA compile
  cache directory (``MXNET_COMPILE_CACHE_DIR``), so every replica
  after the first warms from disk instead of compiling: scale-out
  and crash replacement cost seconds, not minutes.  A replica is
  READY only after every model in its spec is loaded AND warm.
* **Rolling deploy** — :meth:`deploy` cycles replicas one at a time:
  mark draining at the router (new requests route around it) ->
  DRAIN RPC (bounded wait for every accepted request; the
  machine-readable drain record must report zero abandoned work or
  the deploy aborts loudly) -> STOP + reap -> spawn the successor on
  the new checkpoint (warm from the shared cache) -> readmit once
  probes see it ready.  Zero accepted requests dropped, by
  construction and by drill (ci/fleet_chaos_drill.py).
* **Fleet view** — :meth:`scrape` aggregates every replica's HTTP
  probe surface (``/metrics`` + ``/readyz``) into one dict and
  refreshes the ``fleet_replicas_ready`` gauge — the single pane an
  external orchestrator reads.

Child processes are bounded on the way down too: :meth:`stop` sends
STOP RPCs, then terminates, then kills — a failed drill can not leak
a replica.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import tempfile
import time as _time

from .buckets import ServeError
from .replica import MSG_DRAIN, MSG_STATS, MSG_STOP
from .router import _REPLICAS_READY, Router
from .. import sanitizer as _san
from ..observability import events as _obs_events
from ..observability import metrics as _obs_metrics

__all__ = ["Fleet", "parse_exposition"]

log = logging.getLogger(__name__)

_DEPLOYS = _obs_metrics.counter(
    "fleet_deploys_total",
    "rolling deploys completed across the fleet")


def parse_exposition(text):
    """Prometheus text exposition -> {metric name: float} for the
    plain counter/gauge samples (histogram series keep their
    ``_bucket``/``_sum``/``_count`` suffixes)."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) != 2:
            continue
        try:
            out[parts[0]] = float(parts[1])
        except ValueError:
            continue
    return out


class Fleet:
    """N replica processes behind one router.

    Parameters
    ----------
    model_specs : list of dict
        Per-model replica spec entries:
        ``{"name", "prefix", "epoch", "data_shapes", "batches"}``
        (see ``serve.replica.main`` for the schema).
    replicas : int
        Fleet size (default 3).
    compile_cache_dir : str, optional
        Shared persistent XLA compile cache for every replica
        (default: ``<workdir>/compile_cache``).  Replicas after the
        first warm from it.
    workdir : str, optional
        Where spec files / logs live (default: a fresh tempdir).
    max_wait_ms : float, optional
        Replica batcher coalescing window override.
    env : dict, optional
        Extra environment for every replica process.
    router_kwargs : dict, optional
        Passed to the :class:`Router` constructor.
    spawn_timeout : float
        Seconds to wait for a replica's READY line (the first replica
        pays real compiles; the rest hit the cache).
    """

    def __init__(self, model_specs, replicas=3, compile_cache_dir=None,
                 workdir=None, max_wait_ms=None, env=None,
                 router_kwargs=None, spawn_timeout=300.0):
        self.model_specs = list(model_specs)
        self.size = int(replicas)
        self.workdir = workdir or tempfile.mkdtemp(prefix="mxnet_fleet_")
        self.compile_cache_dir = compile_cache_dir or os.path.join(
            self.workdir, "compile_cache")
        self.max_wait_ms = max_wait_ms
        self._extra_env = dict(env or {})
        self._spawn_timeout = float(spawn_timeout)
        self.router = Router(**(router_kwargs or {}))
        self._lock = _san.lock(label="serve.fleet")
        self._procs = {}        # key -> record dict
        self._next_id = 0
        _san.track(self, ("_procs", "_next_id"), label="serve.fleet")

    # -- spawning ----------------------------------------------------------
    def _write_spec(self, name, model_specs):
        spec = {"name": name, "models": model_specs}
        if self.max_wait_ms is not None:
            spec["max_wait_ms"] = float(self.max_wait_ms)
        path = os.path.join(self.workdir, "%s.spec.json" % name)
        with open(path, "w") as f:
            json.dump(spec, f)
        return path

    def _spawn(self, model_specs=None, extra_env=None):
        """Start one replica process, wait for its READY line, and
        register it with the router.  Returns the replica key."""
        with self._lock:
            rid = self._next_id
            self._next_id += 1
        name = "replica-%d" % rid
        spec_path = self._write_spec(name,
                                     model_specs or self.model_specs)
        env = dict(os.environ)
        env.update(self._extra_env)
        env.update(extra_env or {})
        env["MXNET_COMPILE_CACHE_DIR"] = self.compile_cache_dir
        # make the package importable regardless of the caller's cwd
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else "")
        # -c instead of -m: runpy would re-execute serve.replica on
        # top of the already-imported package module (RuntimeWarning)
        proc = subprocess.Popen(
            [sys.executable, "-c",
             "import sys; from mxnet_tpu.serve.replica import main; "
             "sys.exit(main())",
             "--spec", spec_path, "--port", "0", "--http-port", "0"],
            env=env, stdout=subprocess.PIPE, text=True)
        ready = {}
        done = _san.event()

        def _read_stdout():
            for line in proc.stdout:
                if line.startswith("REPLICA READY"):
                    for part in line.split()[2:]:
                        k, _, v = part.partition("=")
                        ready[k] = int(v)
                    done.set()
            done.set()      # EOF without READY: spawn failed

        reader = _san.thread(target=_read_stdout,
                             name="fleet-stdout-%s" % name, daemon=True)
        reader.start()
        if not done.wait(self._spawn_timeout) or "port" not in ready:
            proc.kill()
            proc.wait(timeout=10)
            raise ServeError(
                "replica %s did not come up within %.0fs (rc=%s)"
                % (name, self._spawn_timeout, proc.poll()))
        handle = self.router.add_replica(
            ("127.0.0.1", ready["port"], ready.get("http", 0)))
        record = {"key": handle.key, "name": name, "proc": proc,
                  "port": ready["port"], "http_port": ready.get("http", 0),
                  "pid": ready.get("pid"), "spec_path": spec_path,
                  "models": list(model_specs or self.model_specs)}
        with self._lock:
            self._procs[handle.key] = record
        _obs_events.emit("fleet", kind="spawn", replica=handle.key,
                         name=name, pid=record["pid"])
        return handle.key

    def start(self):
        """Spawn the whole fleet (sequential: the first replica
        populates the compile cache the rest warm from) and wait
        until the router can route to every one.  Returns self."""
        for _ in range(self.size):
            self._spawn()
        self.wait_routable(count=self.size)
        return self

    def keys(self):
        with self._lock:
            return sorted(self._procs)

    def record(self, key):
        with self._lock:
            return dict(self._procs[key])

    def wait_routable(self, count=None, model=None, timeout=60.0):
        """Block until *count* replicas (default: the whole fleet)
        are routable for *model* per the router's probes."""
        count = self.size if count is None else count
        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            self.router.probe_once()
            if self.router.ready_count(model) >= count:
                return True
            _time.sleep(0.05)
        raise ServeError(
            "only %d/%d replicas routable after %.0fs"
            % (self.router.ready_count(model), count, timeout))

    # -- teardown / replacement --------------------------------------------
    def _reap(self, key, rpc_stop=True, timeout=15.0):
        """Stop one replica process, bounded: STOP RPC -> wait ->
        terminate -> kill.  Removes it from the router."""
        with self._lock:
            record = self._procs.pop(key, None)
        self.router.remove_replica(key)
        if record is None:
            return None
        proc = record["proc"]
        if rpc_stop and proc.poll() is None:
            # the router handle is gone: one direct best-effort STOP
            try:
                from .router import ReplicaHandle
                h = ReplicaHandle("127.0.0.1", record["port"])
                self.router._call(h, MSG_STOP, {}, timeout=5.0)
                h.close_pool()
            except (ConnectionError, OSError, ServeError):
                pass
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
        _obs_events.emit("fleet", kind="reap", replica=key,
                         rc=proc.returncode)
        return record

    def replace(self, key, model_specs=None, extra_env=None):
        """Replace a (dead or retiring) replica with a fresh spawn —
        the crash-recovery path ci/fleet_chaos_drill.py drives after
        a replica kill.  Returns the successor's key."""
        self._reap(key)
        return self._spawn(model_specs=model_specs,
                           extra_env=extra_env)

    def stop(self, timeout=15.0):
        """Tear the whole fleet down, bounded (a failed drill must
        not leak replica processes)."""
        for key in self.keys():
            self._reap(key, timeout=timeout)
        self.router.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- rolling deploy ----------------------------------------------------
    def deploy(self, model_specs, drain_timeout=None):
        """Drain-aware rolling deploy: cycle replicas one at a time
        onto *model_specs* (the new checkpoint) — drain -> swap ->
        warm from the shared compile cache -> readmit — dropping zero
        accepted requests.  Live streaming decode sessions are
        MIGRATED, not waited out: the DRAIN evicts them with the
        typed ``draining`` code and the router re-opens each on a
        healthy replica from its journal (same handle, bit-equal
        resume).  A drain that times out (abandoned accepted work)
        aborts the deploy loudly.  Returns the list of successor
        replica keys."""
        model_specs = list(model_specs)
        names = sorted({m["name"] for m in model_specs})
        _obs_events.emit("fleet", kind="deploy_start", models=names,
                         replicas=self.keys())
        from ..config import get_env
        per_model_drain = (float(drain_timeout)
                           if drain_timeout is not None
                           else get_env("MXNET_SERVE_DRAIN_TIMEOUT"))
        successors = []
        for key in self.keys():
            self.router.set_draining(key, True)
            dead = self.record(key)["proc"].poll() is not None
            if not dead:
                # the RPC's socket timeout must outlive the WHOLE
                # drain (drain_all waits per model, sequentially) —
                # with the default 60s RPC timeout a long legitimate
                # drain would otherwise surface as a transport
                # failure and skip the resume path below
                n_models = max(1, len(self.record(key)["models"]))
                rpc_budget = per_model_drain * n_models + 30.0
                try:
                    stats, _ = self.router.control(
                        key, MSG_DRAIN, {"timeout": drain_timeout},
                        timeout=rpc_budget)
                except ConnectionError as exc:
                    if self.record(key)["proc"].poll() is not None:
                        stats = {}      # died mid-drain: replace it
                    else:
                        # alive but unreachable: hand it back and
                        # abort — never reap a replica that may still
                        # hold accepted work we could not drain
                        try:
                            self.router.control(key, MSG_DRAIN,
                                                {"resume": True})
                        except (ConnectionError, ServeError):
                            pass
                        self.router.set_draining(key, False)
                        raise ServeError(
                            "deploy aborted: DRAIN RPC to live "
                            "replica %s failed in transport (%s) — "
                            "replica resumed, fleet unchanged"
                            % (key, exc)) from exc
                if stats.get("timed_out"):
                    # hand the replica BACK to service before
                    # aborting: without the resume it would shed
                    # every predict (draining) for the rest of its
                    # life — a silent one-replica-short fleet
                    try:
                        self.router.control(key, MSG_DRAIN,
                                            {"resume": True})
                    except (ConnectionError, ServeError):
                        pass    # the abort below is the headline
                    self.router.set_draining(key, False)
                    raise ServeError(
                        "deploy aborted: replica %s drain timed out "
                        "with %d accepted requests outstanding — "
                        "accepted work is never dropped (replica "
                        "resumed, fleet unchanged)"
                        % (key, stats.get("waited_requests", -1)))
                _obs_events.emit(
                    "fleet", kind="deploy_drain", replica=key,
                    waited_requests=stats.get("waited_requests"),
                    decode_evicted=stats.get("decode_evicted", 0),
                    timed_out=False)
            new_key = self.replace(key, model_specs=model_specs)
            # the successor is only READY after load+warm (spawn
            # gates on the READY line), but wait for the router's own
            # probes before moving to the next replica so the fleet
            # never has two replicas out of rotation at once
            self.wait_routable(count=len(self.keys()), model=None)
            successors.append(new_key)
            _obs_events.emit("fleet", kind="deploy_replica",
                             replica=key, successor=new_key)
        self.model_specs = model_specs
        _DEPLOYS.inc()
        _obs_events.emit("fleet", kind="deploy", models=names,
                         replicas=successors)
        return successors

    # -- fleet view --------------------------------------------------------
    def stats(self, key):
        """One replica's STATS RPC (dispatch/dedup/compile counters —
        the drill's exactly-once evidence)."""
        rmeta, _ = self.router.control(key, MSG_STATS, {})
        return rmeta

    def scrape(self, timeout=5.0):
        """Aggregate every replica's HTTP probe surface into one
        fleet view::

            {"replicas": {key: {"ready": bool, "readyz": {...},
                                "metrics": {name: value}}},
             "ready": N, "size": M}

        and refresh the ``fleet_replicas_ready`` gauge.  Replicas
        without a probe port (http_port 0) report ``scraped: False``.
        """
        import urllib.error
        import urllib.request
        view = {"replicas": {}, "size": len(self.keys())}
        ready = 0
        for key in self.keys():
            record = self.record(key)
            entry = {"scraped": False, "ready": False}
            port = record.get("http_port")
            if port:
                base = "http://127.0.0.1:%d" % port
                try:
                    with urllib.request.urlopen(base + "/readyz",
                                                timeout=timeout) as r:
                        entry["readyz"] = json.loads(r.read().decode())
                        entry["ready"] = True
                except urllib.error.HTTPError as e:
                    try:
                        entry["readyz"] = json.loads(e.read().decode())
                    except ValueError:
                        pass
                except (OSError, ValueError) as e:
                    entry["error"] = str(e)[:200]
                try:
                    with urllib.request.urlopen(base + "/metrics",
                                                timeout=timeout) as r:
                        entry["metrics"] = parse_exposition(
                            r.read().decode())
                        entry["scraped"] = True
                except (OSError, ValueError) as e:
                    entry.setdefault("error", str(e)[:200])
            view["replicas"][key] = entry
            ready += bool(entry["ready"])
        view["ready"] = ready
        _REPLICAS_READY.set(self.router.ready_count())
        return view
