"""ReplicaServer — one serving process behind a socket RPC surface.

A replica wraps a :class:`~mxnet_tpu.serve.registry.ModelRegistry`
behind the SAME length-framed wire format the distributed kvstore
uses (``_kvstore_impl``'s ``frame := u64 len | u8 kind | json meta |
tensors`` — one wire format in the codebase, two consumers, no
drift), so a fleet of N replica processes fronted by a
:class:`~mxnet_tpu.serve.router.Router` gets the process-level fault
model the training stack already has:

* **Idempotent predicts** — every PREDICT carries a
  ``(client, seq, incarnation)`` request id (the PR-7 kvstore
  discipline); the replica keeps a per-client dedup window whose
  first arrival executes and publishes the reply, while duplicates
  (router retry after a torn connection, the losing half of a hedged
  pair) wait and answer from cache with ``dup: true`` — a retried
  predict is never double-dispatched on one replica.
* **Cancellation through the window** — CANCEL marks the id's window
  entry and cancels its in-flight future, so a hedge loser is
  reclaimed before dispatch when possible and a LATE arrival of a
  cancelled id answers ``cancelled`` from cache instead of running.
* **Typed errors over the wire** — shedding, deadlines, drains and
  internal failures reply with a ``code`` the router maps back onto
  the same typed exception classes (:class:`OverloadError`,
  :class:`DeadlineExceededError`, ...), never a silent drop.
* **Streaming decode over the wire** — ``DECODE_OPEN`` / ``NEXT`` /
  ``CANCEL`` / ``CLOSE`` expose the continuous-batching decode path
  with the same discipline: OPEN is keyed by ``(client, session_seq)``
  and is idempotent (a retried OPEN reuses the live session; a resume
  OPEN carries the router's journaled tokens and replays them
  bit-checked), NEXT(i) answers token *i* from the session's retained
  stream — a retried index is served from cache, never re-decoded —
  and blocks bounded (a not-yet-decoded index answers ``pending`` so
  the router polls instead of hanging), and a DRAIN evicts live wire
  sessions with the typed ``draining`` code so the router migrates
  them to a successor from its journal instead of waiting out long
  streams.
* **Probe surface** — the PR-10 health state machine is exported two
  ways: a HEALTH RPC for the router's heartbeat loop, and a stdlib
  ``http.server`` probe endpoint (``MXNET_SERVE_HTTP_PORT``) serving
  ``/metrics`` (Prometheus exposition of the whole process registry),
  ``/healthz`` (liveness) and ``/readyz`` (readiness + per-model
  health JSON) for external orchestrators.

Fleet chaos (``replica_kill_at`` / ``slow_replica_ms``) is consulted
at the PREDICT choke point, so ci/fleet_chaos_drill.py drives the
exact failover path a real replica death exercises.

``python -m mxnet_tpu.serve.replica --spec spec.json`` is the process
entry the :class:`~mxnet_tpu.serve.fleet.Fleet` spawns; it loads the
spec's checkpoints (warming from the shared persistent XLA compile
cache when ``MXNET_COMPILE_CACHE_DIR`` is set), starts serving, and
prints one ``REPLICA READY port=.. http=.. pid=..`` line for the
parent to scrape.
"""

from __future__ import annotations

import collections
import json
import logging
import socket

import numpy as _np

from .buckets import (BucketLadder, DeadlineExceededError,
                      OverloadError, RequestCancelled, ServeError)
from .. import sanitizer as _san
from ..observability import events as _obs_events
from ..observability import metrics as _obs_metrics
from ..resilience import servechaos as _servechaos

__all__ = ["ReplicaServer", "ReplicaDraining", "start_http_probe",
           "MSG_PREDICT", "MSG_HEALTH", "MSG_LOAD", "MSG_UNLOAD",
           "MSG_DRAIN", "MSG_STATS", "MSG_CANCEL", "MSG_STOP",
           "MSG_DECODE_OPEN", "MSG_DECODE_NEXT", "MSG_DECODE_CANCEL",
           "MSG_DECODE_CLOSE", "MSG_REPLY", "error_code",
           "error_class"]

log = logging.getLogger(__name__)

# wire message kinds (the framing itself is _kvstore_impl's; these
# kinds are the serve protocol's own namespace — replicas listen on
# their own port, so there is no overlap with the kvstore kinds)
MSG_REPLY = 0
MSG_PREDICT = 1
MSG_HEALTH = 2
MSG_LOAD = 3
MSG_UNLOAD = 4
MSG_DRAIN = 5
MSG_STATS = 6
MSG_CANCEL = 7
MSG_STOP = 8
MSG_DECODE_OPEN = 9
MSG_DECODE_NEXT = 10
MSG_DECODE_CANCEL = 11
MSG_DECODE_CLOSE = 12

_REPLICA_REQUESTS = _obs_metrics.counter(
    "fleet_replica_requests_total",
    "predict RPCs received by this replica (dedup hits included)")
_REPLICA_DUP_HITS = _obs_metrics.counter(
    "fleet_replica_dedup_hits_total",
    "predict RPCs answered from the idempotency window instead of "
    "re-dispatched (router retries, hedge losers)")

class ReplicaDraining(ServeError):
    """Shed at admission because this replica is draining (deploy in
    progress).  The request was never dispatched, so the router may
    safely reroute it to another replica — the zero-drop half of the
    rolling-deploy contract."""


# typed serve errors <-> wire codes: the router re-raises the SAME
# class the replica's registry raised, so fleet callers see exactly
# the single-process error contract
_CODE_FOR = (
    (ReplicaDraining, "draining"),
    (OverloadError, "overload"),          # KVPoolExhausted included
    (DeadlineExceededError, "deadline"),
    (RequestCancelled, "cancelled"),
    (TimeoutError, "timeout"),
    (ServeError, "serve"),
)
_CLASS_FOR = {
    "draining": ReplicaDraining,
    "overload": OverloadError,
    "deadline": DeadlineExceededError,
    "cancelled": RequestCancelled,
    "timeout": ServeError,
    "serve": ServeError,
    "internal": ServeError,
}


def error_code(exc):
    """The wire code for a serve-side exception (docs/serving.md
    "Serving fleet" wire-protocol table)."""
    for cls, code in _CODE_FOR:
        if isinstance(exc, cls):
            return code
    return "internal"


def error_class(code):
    """The typed exception class the router raises for a wire code."""
    return _CLASS_FOR.get(code, ServeError)


class _Pending:
    """One idempotency-window entry (the kvstore's ``_InFlight``
    shape): the first arrival of a request id owns it and publishes
    the full reply through ``event``; duplicates wait on the event
    and answer from ``result`` with ``dup: true``."""

    __slots__ = ("event", "result", "future", "cancelled")

    def __init__(self):
        self.event = _san.event()
        self.result = None      # (reply meta, reply tensors)
        self.future = None      # live ServeFuture while dispatching
        self.cancelled = False


class ReplicaServer:
    """One serving replica: a ModelRegistry behind the kvstore wire
    framing, with idempotent predicts and the probe surface a fleet
    router needs.

    Parameters
    ----------
    registry : ModelRegistry, optional
        Created fresh when omitted.
    host, port : bind address (port 0 = ephemeral, read ``.port``).
    http_port : int, optional
        Probe endpoint port (0 = ephemeral; None = consult
        ``MXNET_SERVE_HTTP_PORT``, whose 0 default means off).
    name : str, optional
        Replica id used in events/chaos blame (default host:port).
    """

    def __init__(self, registry=None, host="127.0.0.1", port=0,
                 http_port=None, name=None):
        from .registry import ModelRegistry
        from ..config import get_env
        self.registry = registry if registry is not None \
            else ModelRegistry()
        self._dedup_window = max(8, get_env("MXNET_SERVE_DEDUP_WINDOW"))
        self._rpc_timeout = get_env("MXNET_SERVE_RPC_TIMEOUT")
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.host = host
        self.port = self.sock.getsockname()[1]
        self.sock.listen(64)
        self.name = name or ("%s:%d" % (self.host, self.port))
        self._lock = _san.lock(label="serve.replica.%s" % self.name)
        self._dedup = {}        # (client, inc) -> OrderedDict(seq -> _Pending)
        self._draining = False
        self._stop = _san.event()
        self._thread = None
        self._predicts_dispatched = 0   # the exactly-once proof counter
        self._requests_received = 0
        self._dup_hits = 0
        self._cancels_received = 0
        # wire decode surface: name -> DecodeBatcher, and the session
        # map keyed by the (client, session_seq) identity — the
        # session's retained output stream IS the NEXT dedup cache
        self._decoders = collections.OrderedDict()
        self._dsessions = collections.OrderedDict()
        self._decode_requests = 0
        _san.track(self, ("_dedup", "_draining",
                          "_predicts_dispatched", "_requests_received",
                          "_dup_hits", "_cancels_received",
                          "_decoders", "_dsessions",
                          "_decode_requests"),
                   label="serve.replica.%s" % self.name)
        self.http_server = None
        if http_port is None:
            knob = get_env("MXNET_SERVE_HTTP_PORT")
            http_port = knob if knob else None
        if http_port is not None:
            self.http_server = start_http_probe(
                self.registry, port=http_port, replica=self)
        self.http_port = self.http_server.server_address[1] \
            if self.http_server is not None else 0

    @property
    def draining(self):
        """Has this replica been told to drain (DRAIN RPC)?  A
        draining replica keeps answering in-flight work but reports
        not-ready on every probe surface."""
        with self._lock:
            return self._draining

    @property
    def predicts_dispatched(self):
        """Predicts actually dispatched to the registry (dedup hits
        excluded) — the per-replica exactly-once proof counter."""
        with self._lock:
            return self._predicts_dispatched

    @property
    def requests_received(self):
        with self._lock:
            return self._requests_received

    @property
    def dup_hits(self):
        with self._lock:
            return self._dup_hits

    @property
    def cancels_received(self):
        with self._lock:
            return self._cancels_received

    @property
    def decode_requests(self):
        with self._lock:
            return self._decode_requests

    # -- wire decode surface -----------------------------------------------
    def add_decoder(self, name, batcher):
        """Expose *batcher* (a :class:`~mxnet_tpu.serve.decode.
        DecodeBatcher`) over the DECODE_* wire surface as model
        *name*.  Returns the batcher."""
        with self._lock:
            self._decoders[name] = batcher
        return batcher

    def decoders(self):
        with self._lock:
            return dict(self._decoders)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        """Accept connections on a background thread; returns self."""
        self._thread = _san.thread(
            target=self.run, name="serve-replica-%s" % self.name,
            daemon=True)
        self._thread.start()
        return self

    def run(self):
        """Accept loop (blocks; the CLI entry's main thread)."""
        self.sock.settimeout(0.5)
        conns = []
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = _san.thread(target=self._serve_conn, args=(conn,),
                            daemon=True)
            t.start()
            # prune sockets their handler already closed (fileno -1):
            # a router that reconnects per breaker trip must not make
            # this list grow for the replica's lifetime
            conns = [c for c in conns if c.fileno() != -1]
            conns.append(conn)
        # an in-process stop must look like a process death to peers:
        # shut every accepted connection so blocked conn threads wake
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def stop(self):
        """Stop accepting and close the listen socket (idempotent).
        Loaded models stay; close the registry separately (the CLI
        entry and the fleet's deploy path do)."""
        self._stop.set()
        try:
            self.sock.close()
        except OSError:
            pass
        # swap-then-close: stop() races with itself when a STOP rpc
        # and the CLI's finally both tear down — only one closer wins
        with self._lock:
            http, self.http_server = self.http_server, None
        if http is not None:
            http.shutdown()
            http.server_close()

    def wait(self, timeout=None):
        """Block until the accept loop stops (CLI main thread)."""
        return self._stop.wait(timeout)

    def close(self):
        self.stop()
        for b in self.decoders().values():
            try:
                b.close()
                b.engine.close()
            except Exception:
                log.exception("replica %r: decoder close failed",
                              self.name)
        self.registry.close()

    # -- connection handling -----------------------------------------------
    def _serve_conn(self, conn):
        from .._kvstore_impl import _recv_frame, _send_frame
        try:
            while not self._stop.is_set():
                try:
                    kind, meta, tensors = _recv_frame(conn)
                except (ConnectionError, OSError, ValueError):
                    return
                try:
                    rmeta, rtensors = self._handle(kind, meta, tensors)
                except Exception as exc:   # typed error over the wire
                    rmeta, rtensors = {
                        "status": "err", "code": error_code(exc),
                        "msg": "%s: %s" % (type(exc).__name__,
                                           str(exc)[:500])}, ()
                try:
                    _send_frame(conn, MSG_REPLY, rmeta, rtensors)
                except (ConnectionError, OSError):
                    return
                if kind == MSG_STOP and rmeta.get("status") == "ok":
                    self.stop()
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, kind, meta, tensors):
        if kind == MSG_PREDICT:
            return self._handle_predict(meta, tensors)
        if kind == MSG_HEALTH:
            return self._handle_health(meta)
        if kind == MSG_CANCEL:
            return self._handle_cancel(meta)
        if kind == MSG_DECODE_OPEN:
            return self._handle_decode_open(meta, tensors)
        if kind == MSG_DECODE_NEXT:
            return self._handle_decode_next(meta)
        if kind == MSG_DECODE_CANCEL:
            return self._handle_decode_cancel(meta)
        if kind == MSG_DECODE_CLOSE:
            return self._handle_decode_close(meta)
        if kind == MSG_LOAD:
            return self._handle_load(meta)
        if kind == MSG_UNLOAD:
            self.registry.unload(meta["model"],
                                 drain=bool(meta.get("drain", True)))
            return {"status": "ok"}, ()
        if kind == MSG_DRAIN:
            if meta.get("resume"):
                # undo a drain (aborted deploy): reopen admissions
                resumed = self.registry.resume_all()
                with self._lock:
                    self._draining = False
                _obs_events.emit("fleet", kind="replica_resume",
                                 replica=self.name, models=resumed)
                return {"status": "ok", "resumed": resumed}, ()
            with self._lock:
                self._draining = True
            # evict live wire decode sessions BEFORE draining the
            # registry: each fails typed 'draining', so the router
            # migrates it to a successor from its journal instead of
            # this drain waiting out (or killing) long streams
            evicted = self._evict_decode_sessions()
            stats = self.registry.drain_all(meta.get("timeout"))
            stats = dict(stats, decode_evicted=evicted)
            _obs_events.emit("fleet", kind="replica_drain",
                             replica=self.name, **stats)
            return dict(stats, status="ok"), ()
        if kind == MSG_STATS:
            return self._handle_stats()
        if kind == MSG_STOP:
            return {"status": "ok"}, ()
        raise ServeError("replica %r: unknown message kind %d"
                         % (self.name, kind))

    # -- predict with the idempotency window -------------------------------
    def _publish(self, ent, result):
        """Publish *result* as THE answer for an id — exactly once.
        A cancel and the owner's dispatch can race; whichever
        publishes first wins and every reader (owner reply included)
        returns the SAME cached answer, so duplicates of one id can
        never observe two different replies."""
        with self._lock:
            if not ent.event.is_set():
                ent.result = result
                ent.event.set()
            return ent.result

    def _handle_predict(self, meta, tensors):
        # fleet chaos choke point: kill/slow BEFORE dedup or dispatch,
        # so an armed kill dies holding the request — the router must
        # see the connection drop and fail the request over
        _servechaos.on_replica_request(self.name)
        _REPLICA_REQUESTS.inc()
        with self._lock:
            self._requests_received += 1
        req = meta.get("req")
        if req is None:
            return self._execute_predict(meta, tensors)
        client, seq, inc = req[0], int(req[1]), int(req[2])
        with self._lock:
            fresh_window = (client, inc) not in self._dedup
            window = self._dedup.setdefault((client, inc),
                                            collections.OrderedDict())
            ent = window.get(seq)
            owner = ent is None
            if owner:
                ent = _Pending()
                window[seq] = ent
                # trim COMPLETED entries past the window bound;
                # in-flight entries are never trimmed (their retries
                # must keep finding them)
                while len(window) > self._dedup_window:
                    oldest = next(iter(window))
                    if not window[oldest].event.is_set():
                        break
                    del window[oldest]
            if fresh_window:
                # bound incarnation buckets per client (the kvstore's
                # <= 4 rule): every router restart mints a new
                # incarnation, and dead ones — window + cached reply
                # tensors — must not accumulate for the replica's
                # lifetime.  Only fully-settled buckets are dropped.
                same = sorted(k for k in self._dedup if k[0] == client)
                for old in same[:-4]:
                    if all(p.event.is_set()
                           for p in self._dedup[old].values()):
                        del self._dedup[old]
        if not owner:
            with self._lock:
                self._dup_hits += 1
            _REPLICA_DUP_HITS.inc()
            if not ent.event.wait(self._rpc_timeout or None):
                raise ServeError(
                    "replica %r: duplicate of (%s, %d, %d) timed out "
                    "waiting for the first arrival's reply"
                    % (self.name, client, seq, inc))
            rmeta, rtensors = ent.result
            rmeta = dict(rmeta)
            rmeta["dup"] = True
            return rmeta, rtensors
        try:
            result = self._execute_predict(meta, tensors, ent)
        except Exception as exc:
            # failed ids leave the window (the kvstore rule): a retry
            # after a transient failure re-executes instead of
            # replaying the error from cache.  Cancelled ids STAY —
            # the hedge loser's late retry must answer 'cancelled'.
            if not isinstance(exc, RequestCancelled) \
                    and not ent.cancelled:
                with self._lock:
                    win = self._dedup.get((client, inc))
                    if win is not None and win.get(seq) is ent:
                        del win[seq]
            # reply with whatever got published first (a racing
            # cancel may have won) — owner and duplicates must tell
            # one story per id
            return self._publish(
                ent, ({"status": "err", "code": error_code(exc),
                       "msg": "%s: %s" % (type(exc).__name__,
                                          str(exc)[:500])}, ()))
        # a racing CANCEL may have published first — return whatever
        # is cached so every reply for this id says the same thing
        return self._publish(ent, result)

    def _execute_predict(self, meta, tensors, ent=None):
        if self.draining:
            # shed BEFORE dispatch with the distinct 'draining' code:
            # the router reroutes (the request never ran here), which
            # is what makes a rolling deploy zero-drop even for the
            # submits that race the drain
            raise ReplicaDraining(
                "replica %r is draining — rerouting" % self.name)
        model = meta["model"]
        names = meta.get("inputs") or []
        if not names and len(tensors) == 1:
            # bare single-input request: the registry's submit maps
            # it onto the model's one data input
            data = tensors[0]
        elif len(names) != len(tensors):
            raise ServeError(
                "replica %r: %d input names for %d tensors"
                % (self.name, len(names), len(tensors)))
        else:
            data = dict(zip(names, tensors))
        deadline_ms = meta.get("deadline_ms")
        try:
            fut = self.registry.submit(model, data,
                                       deadline_ms=deadline_ms)
        except ServeError as exc:
            if self.draining and not isinstance(
                    exc, (OverloadError, DeadlineExceededError,
                          RequestCancelled, ReplicaDraining)):
                # the batcher's own draining shed (plain ServeError)
                # raced the check above: a DRAIN landed between them.
                # Re-code it as reroutable so the deploy stays
                # zero-drop for submits inside the race window.
                raise ReplicaDraining(
                    "replica %r is draining — rerouting"
                    % self.name) from exc
            raise
        if ent is not None:
            with self._lock:
                if ent.cancelled:
                    # CANCEL raced the dispatch: reclaim the slot now
                    fut.cancel()
                else:
                    ent.future = fut
        budget = (float(deadline_ms) / 1e3 + 5.0) if deadline_ms \
            else (self._rpc_timeout or 60.0)
        try:
            outs = fut.result(budget)
        except TimeoutError:
            fut.cancel()
            raise
        with self._lock:
            self._predicts_dispatched += 1
        return ({"status": "ok", "outputs": len(outs)},
                [_np.asarray(o) for o in outs])

    def _handle_cancel(self, meta):
        """Hedge-loser / abandoned-request cancellation through the
        idempotency window: reclaim the queued slot when possible,
        and pin the id as cancelled so a LATE arrival answers
        ``cancelled`` from cache instead of dispatching."""
        req = meta["req"]
        client, seq, inc = req[0], int(req[1]), int(req[2])
        with self._lock:
            self._cancels_received += 1
            window = self._dedup.setdefault((client, inc),
                                            collections.OrderedDict())
            ent = window.get(seq)
            if ent is None:
                ent = _Pending()
                window[seq] = ent
            ent.cancelled = True
            fut = ent.future
        reclaimed = bool(fut.cancel()) if fut is not None else False
        if fut is None:
            # never dispatched here (or not yet): publish the typed
            # cancelled reply so any waiter/late duplicate gets it —
            # through _publish, so an owner racing past the cancelled
            # check cannot later overwrite it with a second answer
            self._publish(ent, ({"status": "err", "code": "cancelled",
                                 "msg": "RequestCancelled: cancelled "
                                        "by the router (hedge "
                                        "loser)"}, ()))
        # req_seq, not seq: a bare ``seq`` field would clobber the
        # event envelope's own monotone seq in the JSONL record
        _obs_events.emit("fleet", kind="replica_cancel",
                         replica=self.name, client=client,
                         req_seq=seq, reclaimed=reclaimed)
        return {"status": "ok", "reclaimed": reclaimed}, ()

    # -- wire decode (idempotent streaming sessions) -----------------------
    @staticmethod
    def _out_wire(out):
        """``(out_names, tensors)`` for one delivered output tree —
        dict outputs go as sorted named leaves, anything else as the
        single bare leaf (the shapes :meth:`DecodeEngine._feed`
        accepts)."""
        if isinstance(out, dict):
            names = sorted(out)
            return names, [_np.asarray(out[n]) for n in names]
        return None, [_np.asarray(out)]

    @staticmethod
    def _out_unwire(names, leaves):
        if names:
            return {n: _np.array(a) for n, a in zip(names, leaves)}
        return _np.array(leaves[0])

    def _handle_decode_open(self, meta, tensors):
        # decode chaos choke point first (replica_kill_decode_at):
        # an armed kill dies holding the OPEN, and the router must
        # re-place the session from its journal
        _servechaos.on_replica_decode(self.name)
        with self._lock:
            self._decode_requests += 1
        ident = meta["session"]
        client, seq, inc = ident[0], int(ident[1]), int(ident[2])
        key = (client, seq)
        with self._lock:
            ent = self._dsessions.get(key)
        if ent is not None:
            if ent.get("cancelled"):
                raise RequestCancelled(
                    "decode session (%s, %d) was cancelled — a "
                    "cancelled session is never resumed"
                    % (client, seq))
            if ent["sess"] is not None:
                # duplicate OPEN (router retry after a torn reply):
                # the live session IS the cached answer
                return {"status": "ok", "dup": True,
                        "sid": ent["sess"].sid,
                        "base": ent["base"]}, ()
        if self.draining:
            raise ReplicaDraining(
                "replica %r is draining — open decode session "
                "(%s, %d) elsewhere" % (self.name, client, seq))
        model = meta["model"]
        with self._lock:
            batcher = self._decoders.get(model)
        if batcher is None:
            raise ServeError(
                "replica %r serves no decode model %r (have %s)"
                % (self.name, model, sorted(self.decoders())))
        if batcher.rebuilding:
            # mid-quarantine: shed reroutable, like overload — the
            # router places the session on a healthy replica
            raise OverloadError(
                "replica %r decode model %r is rebuilding its pool — "
                "open elsewhere" % (self.name, model))
        names = meta.get("inputs") or []
        n_in = len(names) if names else 1
        if names:
            prompt = {n: _np.array(t)
                      for n, t in zip(names, tensors[:n_in])}
        else:
            prompt = _np.array(tensors[0])
        resume = []
        count = int(meta.get("resume") or 0)
        if count:
            out_names = meta.get("out_names")
            per = len(out_names) if out_names else 1
            flat = [_np.array(t) for t in tensors[n_in:]]
            if len(flat) != count * per:
                raise ServeError(
                    "decode OPEN (%s, %d): %d resume tensors for %d "
                    "journaled token(s) of %d leaf/leaves"
                    % (client, seq, len(flat), count, per))
            for i in range(count):
                resume.append(self._out_unwire(
                    out_names, flat[i * per:(i + 1) * per]))
        sess = batcher.start(
            prompt, max_new_tokens=meta.get("max_new_tokens"),
            deadline_ms=meta.get("deadline_ms"),
            journal_key=key, incarnation=inc,
            resume_tokens=resume or None)
        entry = {"sess": sess, "model": model, "incarnation": inc,
                 "base": len(resume), "cancelled": False}
        with self._lock:
            old = self._dsessions.get(key)
            if old is not None and old.get("cancelled"):
                # a CANCEL raced this open: honor it
                sess.cancel()
                entry["cancelled"] = True
            self._dsessions[key] = entry
            self._trim_dsessions_locked()
        _obs_events.emit("fleet", kind="decode_open",
                         replica=self.name, model=model,
                         client=str(client), session_seq=seq,
                         incarnation=inc, resumed=len(resume))
        return {"status": "ok", "sid": sess.sid,
                "base": len(resume)}, ()

    def _handle_decode_next(self, meta):
        _servechaos.on_replica_decode(self.name)
        with self._lock:
            self._decode_requests += 1
        ident = meta["session"]
        key = (ident[0], int(ident[1]))
        with self._lock:
            ent = self._dsessions.get(key)
        if ent is None or ent["sess"] is None:
            if ent is not None and ent.get("cancelled"):
                raise RequestCancelled(
                    "decode session (%s, %d) was cancelled"
                    % (key[0], key[1]))
            raise ServeError("replica %r knows no decode session "
                             "(%s, %d)" % (self.name, key[0], key[1]))
        sess = ent["sess"]
        i = int(meta["index"])
        local = i - ent["base"]
        if local < 0:
            raise ServeError(
                "decode session (%s, %d): token %d predates this "
                "replica's resume base %d — the router already holds "
                "it" % (key[0], key[1], i, ent["base"]))
        wait_s = float(meta.get("wait_s") or 10.0)
        if self._rpc_timeout:
            wait_s = min(wait_s, self._rpc_timeout * 0.5)
        try:
            out = sess.output_at(local, timeout=wait_s)
        except StopIteration:
            return {"status": "ok", "done": True,
                    "reason": sess.finish_reason,
                    "total": ent["base"] + sess.token_count}, ()
        except TimeoutError:
            # bounded wait: token *i* is not decoded yet — answer
            # 'pending' so the router polls again instead of the RPC
            # hanging into its transport timeout
            return {"status": "ok", "pending": True, "index": i}, ()
        names, leaves = self._out_wire(out)
        return {"status": "ok", "index": i, "out_names": names}, leaves

    def _handle_decode_cancel(self, meta):
        ident = meta["session"]
        key = (ident[0], int(ident[1]))
        with self._lock:
            self._cancels_received += 1
            ent = self._dsessions.get(key)
            if ent is None:
                # cancel racing a failover re-open: pin the id so a
                # LATE resume OPEN answers cancelled — a cancelled
                # session is never resumed
                ent = {"sess": None, "model": None, "incarnation": -1,
                       "base": 0, "cancelled": True}
                self._dsessions[key] = ent
            else:
                ent["cancelled"] = True
            sess = ent["sess"]
        reclaimed = bool(sess.cancel()) if sess is not None else False
        _obs_events.emit("fleet", kind="decode_cancel",
                         replica=self.name, client=str(key[0]),
                         session_seq=key[1], reclaimed=reclaimed)
        return {"status": "ok", "reclaimed": reclaimed}, ()

    def _handle_decode_close(self, meta):
        ident = meta["session"]
        key = (ident[0], int(ident[1]))
        with self._lock:
            ent = self._dsessions.pop(key, None)
        sess = ent["sess"] if ent else None
        if sess is not None and not sess.done():
            sess.cancel()
        return {"status": "ok", "closed": ent is not None}, ()

    def _trim_dsessions_locked(self):
        # settled entries (finished session or cancel pin) age out
        # past the dedup window; live sessions are never trimmed —
        # their retries must keep finding them
        while len(self._dsessions) > self._dedup_window:
            for k, e in list(self._dsessions.items()):
                if e["sess"] is None or e["sess"].done():
                    del self._dsessions[k]
                    break
            else:
                return

    def _evict_decode_sessions(self):
        """Fail every live wire decode session with the typed
        ``draining`` code — the deploy-migration handoff: the router
        re-opens each on a successor from its journal and the stream
        resumes bit-equal under the same handle."""
        with self._lock:
            entries = [(k, e) for k, e in self._dsessions.items()
                       if e["sess"] is not None]
            decoders = dict(self._decoders)
        evicted = 0
        for key, ent in entries:
            sess = ent["sess"]
            batcher = decoders.get(ent["model"])
            if sess.done() or batcher is None:
                continue
            batcher.engine.release(
                sess, "migrated", ReplicaDraining(
                    "replica %r is draining — resume decode session "
                    "(%s, %d) on a successor"
                    % (self.name, key[0], key[1])))
            evicted += 1
            _obs_events.emit("decode", kind="migrate",
                             replica=self.name, model=ent["model"],
                             client=str(key[0]), session_seq=key[1],
                             tokens=ent["base"] + sess.token_count)
        return evicted

    # -- control plane -----------------------------------------------------
    def _handle_health(self, meta):
        models = {}
        for n, info in self.registry.health().items():
            models[n] = {"state": info.get("state"),
                         "ready": info.get("state") == "ready",
                         "queue_depth": info.get("queue_depth", 0)}
        # wire decode models ride the same surface so the router's
        # eligible(model) placement sees them
        for n, b in self.decoders().items():
            state = b.health_state()
            models.setdefault(n, {
                "state": state, "ready": state == "ready",
                "queue_depth": b.session_count, "decode": True})
        with self._lock:
            draining = self._draining
        return {"status": "ok", "replica": self.name,
                "live": self.registry.live(), "draining": draining,
                "models": models}, ()

    def _handle_load(self, meta):
        ladder = None
        if meta.get("batches"):
            ladder = BucketLadder(batches=tuple(meta["batches"]))
        pred = self.registry.load_checkpoint(
            meta["model"], meta["prefix"], int(meta["epoch"]),
            {n: tuple(s) for n, s in meta["data_shapes"].items()},
            ladder=ladder)
        # eager batcher so readiness probes see dispatcher liveness
        # from the first health RPC, not the first request
        self.registry.batcher(meta["model"])
        with self._lock:
            self._draining = False
        _obs_events.emit("fleet", kind="replica_load",
                         replica=self.name, model=meta["model"],
                         programs=pred.compile_count)
        return {"status": "ok", "programs": pred.compile_count}, ()

    def _handle_stats(self):
        with self._lock:
            stats = {"predicts_dispatched": self._predicts_dispatched,
                     "requests_received": self._requests_received,
                     "dup_hits": self._dup_hits,
                     "cancels_received": self._cancels_received,
                     "decode_requests": self._decode_requests}
        compiles = {}
        for n in self.registry.names():
            try:
                compiles[n] = self.registry.get(n).compile_count
            except ServeError:
                continue
        stats["compile_count"] = compiles
        decode = {}
        for n, b in self.decoders().items():
            decode[n] = dict(b.rebuild_state(),
                             compile_count=b.engine.compile_count,
                             sessions=b.session_count,
                             state=b.health_state())
        stats["decode"] = decode
        return dict(stats, status="ok"), ()


# -- HTTP probe endpoint ------------------------------------------------------

def start_http_probe(registry, port=0, host="127.0.0.1", replica=None):
    """Serve ``/metrics`` (Prometheus exposition of the process
    metrics registry), ``/healthz`` (liveness) and ``/readyz``
    (readiness + per-model health JSON) on a stdlib
    ``ThreadingHTTPServer`` — the scrape surface the fleet router and
    any external orchestrator needs.  Returns the server (call
    ``shutdown()`` + ``server_close()`` to stop); the serving thread
    is daemonic."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _ProbeHandler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):   # quiet by default
            log.debug("probe %s", fmt % args)

        def _send(self, code, body, ctype="application/json"):
            payload = body.encode() if isinstance(body, str) else body
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):
            try:
                if self.path == "/metrics":
                    self._send(200, _obs_metrics.exposition(),
                               ctype="text/plain; version=0.0.4")
                    return
                if self.path == "/healthz":
                    live = registry.live()
                    self._send(200 if live else 503,
                               json.dumps({"live": bool(live)}))
                    return
                if self.path == "/readyz":
                    health = registry.health()
                    draining = bool(replica is not None and
                                    replica.draining)
                    ready = (bool(health) and not draining and
                             all(m.get("state") == "ready"
                                 for m in health.values()))
                    body = {"ready": ready, "draining": draining,
                            "models": {n: m.get("state")
                                       for n, m in health.items()}}
                    self._send(200 if ready else 503,
                               json.dumps(body))
                    return
                self._send(404, json.dumps({"error": "unknown path",
                                            "have": ["/metrics",
                                                     "/healthz",
                                                     "/readyz"]}))
            except Exception as exc:
                log.warning("probe endpoint error on %s: %s",
                            self.path, exc)
                try:
                    self._send(500, json.dumps(
                        {"error": str(exc)[:200]}))
                except OSError:
                    pass

    srv = ThreadingHTTPServer((host, port), _ProbeHandler)
    srv.daemon_threads = True
    t = _san.thread(target=srv.serve_forever,
                    name="serve-probe-%d" % srv.server_address[1],
                    daemon=True)
    t.start()
    return srv


# -- process entry (the fleet's spawn target) ---------------------------------

def main(argv=None):
    """``python -m mxnet_tpu.serve.replica --spec spec.json
    [--port P] [--http-port H]``

    Spec schema::

        {"name": "replica-0",               # optional
         "max_wait_ms": 1.0,                # optional batcher knob
         "models": [{"name": "m", "prefix": "/ckpt/m", "epoch": 3,
                     "data_shapes": {"data": [1, 16]},
                     "batches": [1, 2, 4]},
                    {"name": "lm", "kind": "decode_lm",
                     "vocab": 32, "dim": 16, "seed": 0,
                     "dtype": "float32", "max_len": 32,
                     "block_size": 4, "num_blocks": 24,
                     "rungs": [1, 2, 4]}]}

    A ``"kind": "decode_lm"`` entry builds the deterministic
    ``test_utils.tiny_attention_lm`` (same seed on every replica →
    identical params → bit-equal cross-replica failover) behind a
    :class:`~mxnet_tpu.serve.decode.DecodeBatcher` on the DECODE_*
    wire surface — the fleet chaos drill's streaming workload.

    Loads + warms every model (hitting the shared persistent XLA
    compile cache when ``MXNET_COMPILE_CACHE_DIR`` is set), starts
    the RPC + probe servers, prints one ``REPLICA READY`` line and
    blocks until a STOP RPC."""
    import argparse
    import os as _os
    import sys as _sys

    parser = argparse.ArgumentParser(prog="mxnet_tpu.serve.replica")
    parser.add_argument("--spec", required=True,
                        help="JSON replica spec (models to serve)")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--http-port", type=int, default=0)
    args = parser.parse_args(argv)
    with open(args.spec) as f:
        spec = json.load(f)

    from .registry import ModelRegistry
    registry = ModelRegistry()
    server = ReplicaServer(registry, port=args.port,
                           http_port=args.http_port,
                           name=spec.get("name"))
    batcher_kwargs = {}
    if spec.get("max_wait_ms") is not None:
        batcher_kwargs["max_wait_ms"] = float(spec["max_wait_ms"])
    for m in spec.get("models", ()):
        if m.get("kind") == "decode_lm":
            from ..test_utils import tiny_attention_lm
            from .decode import DecodeBatcher, DecodeEngine
            params, step_fn, prefill_fn, token_spec, input_spec = \
                tiny_attention_lm(vocab=int(m.get("vocab", 32)),
                                  dim=int(m.get("dim", 16)),
                                  seed=int(m.get("seed", 0)),
                                  dtype=m.get("dtype", "float32"))
            eng = DecodeEngine(
                step_fn, prefill_fn=prefill_fn,
                token_spec=token_spec, input_spec=input_spec,
                params=params, max_len=int(m.get("max_len", 32)),
                block_size=int(m["block_size"])
                if m.get("block_size") else None,
                num_blocks=int(m["num_blocks"])
                if m.get("num_blocks") else None,
                session_rungs=tuple(m["rungs"])
                if m.get("rungs") else None,
                label=m["name"])
            server.add_decoder(
                m["name"], DecodeBatcher(eng, name=m["name"],
                                         **batcher_kwargs))
            continue
        ladder = BucketLadder(batches=tuple(m["batches"])) \
            if m.get("batches") else None
        registry.load_checkpoint(
            m["name"], m["prefix"], int(m["epoch"]),
            {n: tuple(s) for n, s in m["data_shapes"].items()},
            ladder=ladder)
        registry.batcher(m["name"], **batcher_kwargs)
    server.start()
    _obs_events.emit("fleet", kind="replica_start",
                     replica=server.name, port=server.port,
                     http=server.http_port, pid=_os.getpid(),
                     models=registry.names()
                     + sorted(server.decoders()))
    print("REPLICA READY port=%d http=%d pid=%d"
          % (server.port, server.http_port, _os.getpid()),
          flush=True)
    try:
        server.wait()
    finally:
        _obs_events.emit("fleet", kind="replica_exit",
                         replica=server.name, pid=_os.getpid())
        server.close()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
