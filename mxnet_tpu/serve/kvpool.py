"""Paged KV-cache pool — fixed device blocks shared by every decode session.

The dense ``DecodeSession`` (predictor.py) gives each session its own
worst-case-length cache: N concurrent sessions pay N full caches of
device memory and N dispatches per token.  This module is the vLLM
paged-attention idea translated to AOT-compiled XLA programs (the
Hybrid JIT/CUDA-Graph low-latency-inference paper in PAPERS.md is the
playbook): allocate ONE fixed pool of cache blocks per model at load
time, hand each session a *block table* of indices into it, and let
the compiled decode-tick program gather/scatter through the table.
Memory is bounded by the pool — thousands of sessions share it, each
holding only the blocks its sequence has actually reached.

Layout, per cache leaf (e.g. per-layer K and V):

    pool leaf:   (num_blocks, block_size, *per_token_shape)
    block table: (max_blocks_per_session,) int32 per session
    dense view:  (S, padded_len, *per_token_shape)   gathered per tick

Block 0 is the reserved **null block**: unused table entries point at
it, padding rows of a partially-filled session rung write their
garbage into it, and no session ever owns it — so a co-tenant's
writes can land there without corrupting anyone (the drill proves
stream bit-equality with the null block deliberately poisoned).

Admission control follows the PR-10 shedding semantics: an ``alloc``
that cannot be satisfied raises the typed :class:`KVPoolExhausted`
(an :class:`~mxnet_tpu.serve.buckets.OverloadError`) instead of
queueing or OOMing — callers shed at the front door, sessions that
exhaust the pool mid-stream fail typed and release their blocks.

Knobs: ``MXNET_SERVE_KV_BLOCK_SIZE`` (tokens per block) and
``MXNET_SERVE_KV_BLOCKS`` (pool capacity).  Gauges
``serve_kv_blocks_in_use`` / ``serve_kv_blocks_total`` are
delta-maintained so multiple pools aggregate (docs/observability.md).
"""

from __future__ import annotations

from .buckets import OverloadError, ServeError
from .. import sanitizer as _san
from ..observability import events as _obs_events
from ..observability import metrics as _obs_metrics

__all__ = ["KVPool", "KVPoolExhausted"]

_BLOCKS_TOTAL = _obs_metrics.gauge(
    "serve_kv_blocks_total",
    "allocatable KV-cache blocks across all live paged pools "
    "(delta-maintained; excludes each pool's reserved null block)")
_BLOCKS_IN_USE = _obs_metrics.gauge(
    "serve_kv_blocks_in_use",
    "KV-cache blocks currently owned by live decode sessions "
    "(delta-maintained across pools)")


class KVPoolExhausted(OverloadError):
    """The paged KV pool has no free block.  Raised at session
    admission (shed at the front door, PR-10 semantics) or when a
    live session's sequence crosses a block boundary with the pool
    full (that session fails typed and releases its blocks)."""


class KVPool:
    """A fixed pool of device-resident cache blocks + its allocator.

    Parameters
    ----------
    token_spec : pytree of jax.ShapeDtypeStruct
        Shape/dtype of ONE token's cache slice per leaf (e.g.
        ``{"k": SDS((heads, dim), f32), "v": ...}``).  Pool leaves are
        allocated as ``(num_blocks, block_size) + leaf.shape``.
    num_blocks : int, optional
        Total blocks including the reserved null block (default the
        ``MXNET_SERVE_KV_BLOCKS`` knob).
    block_size : int, optional
        Tokens per block (default ``MXNET_SERVE_KV_BLOCK_SIZE``).
    device : jax device, optional
        Where the pool lives (default: current context's device).

    The device arrays are exposed as :attr:`arrays` and re-bound by
    the decode engine after every donated program call
    (:meth:`set_arrays`) — the pool object owns the allocator and the
    *current* state handle; program threading is the engine's job.
    """

    def __init__(self, token_spec, num_blocks=None, block_size=None,
                 device=None):
        import jax
        import jax.numpy as jnp
        from ..config import get_env
        from ..context import current_context

        if num_blocks is None:
            num_blocks = get_env("MXNET_SERVE_KV_BLOCKS")
        if block_size is None:
            block_size = get_env("MXNET_SERVE_KV_BLOCK_SIZE")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        if self.block_size < 1:
            raise ServeError("KV block size must be >= 1, got %d"
                             % self.block_size)
        if self.num_blocks < 2:
            raise ServeError(
                "KV pool needs >= 2 blocks (block 0 is the reserved "
                "null block), got %d" % self.num_blocks)
        self._device = device if device is not None \
            else current_context().jax_device
        self._spec = token_spec
        leaves = jax.tree_util.tree_leaves(token_spec)
        if not leaves:
            raise ServeError("KV pool token_spec has no leaves")
        self.arrays = jax.tree_util.tree_map(
            lambda s: jax.device_put(
                jnp.zeros((self.num_blocks, self.block_size)
                          + tuple(s.shape), s.dtype), self._device),
            token_spec)
        # bytes, for operators sizing the pool
        self.bytes_per_block = sum(
            self.block_size * int(jnp.dtype(s.dtype).itemsize)
            * int(_prod(s.shape)) for s in leaves)
        self._lock = _san.lock(label="serve.kvpool")
        # free list: every block except the reserved null block 0
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._in_use = 0
        self._closed = False
        _san.track(self, ("_free", "_in_use", "_closed", "arrays"),
                   label="serve.kvpool")
        _BLOCKS_TOTAL.inc(self.num_blocks - 1)

    # -- state threading (engine-side) --------------------------------------
    def set_arrays(self, arrays):
        """Re-bind the pool state after a donated program call — the
        outputs become the next call's inputs, fused-step style."""
        self.arrays = arrays

    @property
    def device(self):
        return self._device

    # -- allocator ----------------------------------------------------------
    @property
    def blocks_total(self):
        """Allocatable blocks (the null block is not allocatable)."""
        return self.num_blocks - 1

    @property
    def blocks_in_use(self):
        with self._lock:
            return self._in_use

    @property
    def blocks_free(self):
        with self._lock:
            return len(self._free)

    def alloc(self, n, owner="?"):
        """Take *n* blocks; returns their ids.  Raises the typed
        :class:`KVPoolExhausted` (and emits a ``decode`` event) when
        fewer than *n* are free — all-or-nothing, so a partially
        admitted session never strands blocks."""
        n = int(n)
        if n < 1:
            raise ServeError("KV alloc needs n >= 1, got %d" % n)
        with self._lock:
            if self._closed:
                raise ServeError("KV pool is closed")
            if len(self._free) < n:
                free = len(self._free)
                in_use = self._in_use
            else:
                blocks = [self._free.pop() for _ in range(n)]
                self._in_use += n
                _BLOCKS_IN_USE.inc(n)
                return blocks
        _obs_events.emit("decode", kind="pool_exhausted", owner=owner,
                         requested=n, free=free, in_use=in_use,
                         total=self.blocks_total)
        raise KVPoolExhausted(
            "KV pool exhausted: %d block(s) requested, %d free "
            "(%d/%d in use) — shed the session or grow "
            "MXNET_SERVE_KV_BLOCKS" % (n, free, in_use,
                                       self.blocks_total))

    def clone_empty(self):
        """A fresh, empty pool with this pool's token spec, geometry
        and device — the quarantine-and-rebuild primitive: the clone's
        leaf avals are identical, so every AOT tick/prefill program
        built against this pool runs the clone with ZERO new compiles
        (programs depend only on pool shapes/dtypes).  The suspect
        pool itself is quarantined by :meth:`close`."""
        return KVPool(self._spec, num_blocks=self.num_blocks,
                      block_size=self.block_size, device=self._device)

    def free(self, blocks):
        """Return *blocks* to the pool (session end, any reason)."""
        if not blocks:
            return
        with self._lock:
            if self._closed:
                return
            for b in blocks:
                b = int(b)
                if b == 0:
                    raise ServeError("block 0 is the reserved null "
                                     "block — it is never allocated")
                self._free.append(b)
            self._in_use -= len(blocks)
            _BLOCKS_IN_USE.dec(len(blocks))

    def close(self):
        """Release the pool: gauges drop, the device arrays are
        unreferenced (memory returns when the engine drops its
        program handles too).  Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            in_use = self._in_use
            self._in_use = 0
            self._free = []
        if in_use:
            _BLOCKS_IN_USE.dec(in_use)
        _BLOCKS_TOTAL.dec(self.num_blocks - 1)
        self.arrays = None


def _prod(shape):
    out = 1
    for s in shape:
        out *= int(s)
    return out
