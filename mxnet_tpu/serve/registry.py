"""ModelRegistry — multi-model serving with a warm compiled-program cache.

The registry is the process's serving control plane (the TVM lesson:
compiled programs are first-class, keyed artifacts, not an implicit
jit side effect):

* ``load`` builds a :class:`CompiledPredictor` and — by default —
  warms every bucket program up front, so the first request is as fast
  as the thousandth;
* ``alias`` gives one compiled model several routable names
  (``"resnet" -> "resnet-v3"`` style traffic cutovers without a
  recompile); repointing an alias flushes the old target's accepted
  requests so a deploy never drops work it admitted;
* ``drain`` stops a model's admissions and waits (bounded) for its
  accepted requests; ``unload`` drains by default, then tears the
  model, its aliases and its batcher down;
* ``batcher``/``submit`` attach the dynamic batcher to a model by
  name;
* ``health``/``ready``/``live`` expose the per-model state machine
  (see health.py) plus queue depth and dispatcher liveness — the
  readiness/liveness surface a fleet scheduler probes.

Every load/unload/alias/drain/health transition is a ``serve`` event,
every program build is counted and blamed (see predictor.py), and the
C predict ABI (capi_bridge.py) is a thin client of the process-wide
:func:`c_registry` instance.
"""

from __future__ import annotations

from .batcher import DynamicBatcher
from .buckets import BucketLadder, ServeError
from .health import HealthBoard
from .predictor import CompiledPredictor
from .. import iraudit as _iraudit
from .. import sanitizer as _san
from ..observability import events as _obs_events
from ..observability import metrics as _obs_metrics

__all__ = ["ModelRegistry", "c_registry"]

_MODELS_GAUGE = _obs_metrics.gauge(
    "serve_models_loaded",
    "models resident across all serve registries (delta-maintained)")
_DRAINS_TOTAL = _obs_metrics.counter(
    "serve_drains_total",
    "graceful drains started (Registry.drain + unload(drain=True))")
_QUANT_MODELS_GAUGE = _obs_metrics.gauge(
    "serve_quantized_models",
    "quantized models resident across all serve registries "
    "(delta-maintained)")
_QUANT_GATE_FAILURES = _obs_metrics.counter(
    "quant_accuracy_gate_failures_total",
    "quantized loads rejected by the load-time accuracy gate")


class ModelRegistry:
    """Named, warm-cached compiled models."""

    def __init__(self):
        self._lock = _san.rlock(label="serve.registry")
        self._models = {}     # name -> CompiledPredictor
        self._aliases = {}    # alias -> canonical name
        self._batchers = {}   # canonical name -> DynamicBatcher
        self._board = HealthBoard()
        _san.track(self, ("_models", "_aliases", "_batchers"),
                   label="serve.registry")

    # -- loading -----------------------------------------------------------
    def load(self, name, symbol, arg_params, aux_params=None,
             data_shapes=None, ladder=None, data_dtypes=None, ctx=None,
             warm=True, bucket_inputs=None, quantize=None, calib=None,
             calib_batches=None):
        """Register and (by default) warm-compile a model.  Returns
        the :class:`CompiledPredictor`.  Re-loading a live name
        replaces it atomically (aliases keep pointing at the name; the
        displaced predictor's batcher is drained, then closed).  A
        build/warm failure never half-registers: the name is dropped
        from the health board and the error propagates.

        When ``MXNET_TUNING_STORE`` names an autotune store with an
        entry for ``(name, device_kind, "serve")``, the load consults
        it: the tuned ladder applies when no *ladder* argument was
        passed, the entry rides on the predictor (``pred.tuning``)
        for the batcher's scalar knobs, and ``health(name)`` surfaces
        a ``tuning`` section.  Precedence everywhere: explicit
        argument > exported env var > tuned store > registered
        default (docs/autotuning.md).

        *quantize* (``"int8"`` / ``"int8-weight-only"`` / a
        :class:`~mxnet_tpu.quantize.QuantizePolicy` / ``None``) lowers
        the model through ``mxnet_tpu.quantize`` before building the
        rungs.  Weight+activation mode needs ranges: pass *calib* (a
        ``CalibTable`` or a saved table's path) or *calib_batches*
        (representative batches to calibrate on at load).  Every rung
        is then GATED against the fp32 model — int8 compute must be
        present in the lowered StableHLO and accuracy must be within
        the policy's thresholds — or the load fails with a typed
        :class:`~mxnet_tpu.quantize.QuantizationError` and nothing is
        installed.  ``health(name)`` grows a ``quantization`` section
        (docs/quantization.md)."""
        from ..quantize import QuantizePolicy
        policy = QuantizePolicy.coerce(quantize)
        tuning = self._tuning_entry(name)
        if ladder is None and tuning:
            rungs = (tuning.get("config") or {}).get("ladder")
            if rungs:
                ladder = BucketLadder(batches=rungs)

        def _check_not_alias():
            if name in self._aliases:
                raise ServeError(
                    "%r is an alias (for %r) — unalias it before "
                    "loading a model under that name"
                    % (name, self._aliases[name]))

        with self._lock:
            _check_not_alias()      # before paying the warm compiles
            replacing = name in self._models
        if not replacing:
            self._board.transition(name, "loading")
        try:
            qreport = None
            serve_symbol, serve_args, serve_aux = \
                symbol, arg_params, aux_params
            if policy is not None:
                serve_symbol, serve_args, serve_aux, qreport = \
                    self._quantize_build(name, symbol, arg_params,
                                         aux_params, policy, calib,
                                         calib_batches)
            pred = CompiledPredictor(
                serve_symbol, serve_args, aux_params=serve_aux,
                data_shapes=data_shapes, ladder=ladder,
                data_dtypes=data_dtypes, ctx=ctx, name=name,
                bucket_inputs=bucket_inputs)
            if warm:
                if not replacing:
                    self._board.transition(name, "warming")
                built = pred.warm()
            else:
                built = 0
            if policy is not None:
                self._gate_quantized(
                    name, pred, symbol, arg_params, aux_params,
                    data_shapes=data_shapes, data_dtypes=data_dtypes,
                    ctx=ctx, bucket_inputs=bucket_inputs,
                    policy=policy, report=qreport)
        except Exception as exc:
            if not replacing:
                self._board.drop(name)
            _obs_events.emit("serve", kind="load_failed", model=name,
                             error="%s: %s" % (type(exc).__name__,
                                               str(exc)[:200]))
            raise
        pred.tuning = tuning
        with self._lock:
            _check_not_alias()      # racing alias() may have won
            old_batcher = self._batchers.pop(name, None)
            displaced = self._models.get(name)
            if displaced is None:
                _MODELS_GAUGE.inc()  # delta: aggregates across registries
            was_q = displaced is not None and \
                getattr(displaced, "quantization", None) is not None
            if policy is not None and not was_q:
                _QUANT_MODELS_GAUGE.inc()
            elif was_q and policy is None:
                _QUANT_MODELS_GAUGE.dec()
            self._models[name] = pred
            # ready-mark INSIDE the install lock: marking after release
            # let a fully-completed concurrent unload drop the board
            # first, then this write resurrected a ghost 'ready' entry
            # for a model that no longer exists
            self._board.transition(name, "ready")
        if old_batcher is not None:
            # the displaced predictor's accepted requests finish
            # before teardown (deploys must not drop admitted work);
            # unwire its health hook first — a crash-past-budget while
            # draining leftovers must not mark the REPLACEMENT
            # unhealthy on the board
            old_batcher.detach_state_hook()
            old_batcher.drain()
            old_batcher.close()
        if displaced is not None and displaced is not pred:
            # the displaced model's decode sessions are accepted work:
            # finish or typed-fail them, release their pool blocks
            self._drain_decoders(displaced, name)
            for eng in list(getattr(displaced, "_decode_engines", ())):
                eng.close()
        _obs_events.emit("serve", kind="load", model=name,
                         programs=built, warm=bool(warm),
                         buckets=list(pred.ladder.batches),
                         **dict(({"tuned": True} if tuning else {}),
                                **({"quantized": policy.mode}
                                   if policy else {})))
        return pred

    @staticmethod
    def _tuning_entry(name, workload="serve"):
        """The active TuningStore's entry for *name*, or None when no
        store is configured / no entry matches.  A configured-but-
        unreadable store propagates loudly — a deploy pointing at a
        store that is not there must not silently run defaults."""
        from ..autotune.store import lookup
        return lookup(name, workload)

    # -- quantized loading -------------------------------------------------
    @staticmethod
    def _quantize_build(name, symbol, arg_params, aux_params, policy,
                        calib, calib_batches):
        """Lower the fp32 model per *policy*.  Resolves the
        calibration source (table object > saved table path >
        calibrate on *calib_batches* now) and returns the quantized
        (symbol, args, aux, report)."""
        from ..quantize import (CalibTable, QuantizationError,
                                calibrate, quantize_model)
        table = None
        if policy.needs_calib:
            if isinstance(calib, CalibTable):
                table = calib
            elif isinstance(calib, str):
                table = CalibTable.load(calib)
            elif calib is not None:
                raise QuantizationError(
                    "calib must be a CalibTable or a saved table "
                    "path, got %s" % type(calib).__name__)
            elif calib_batches is not None:
                table = calibrate(symbol, arg_params, calib_batches,
                                  aux_params=aux_params, name=name)
            else:
                raise QuantizationError(
                    "load(%r, quantize='int8') needs calibration "
                    "ranges: pass calib= (CalibTable or path) or "
                    "calib_batches=" % name)
        return quantize_model(symbol, arg_params, calib=table,
                              policy=policy, aux_params=aux_params,
                              name=name)

    @staticmethod
    def _gate_quantized(name, pred, symbol, arg_params, aux_params,
                        data_shapes, data_dtypes, ctx, bucket_inputs,
                        policy, report):
        """Load-time gate: at EVERY rung the quantized predictor must
        (a) provably carry int8 compute in its lowered StableHLO and
        (b) agree with an fp32 reference predictor within the policy's
        accuracy thresholds.  Failure increments
        ``quant_accuracy_gate_failures_total`` and raises typed — a
        quantized model never serves silently-wrong answers.  On
        success the report (+ per-rung gate numbers) rides on
        ``pred.quantization`` for ``health()``."""
        import numpy as _np
        from ..quantize import (QuantizationError, hlo_has_int8_compute,
                                hlo_has_int8_tensors)
        ref = CompiledPredictor(
            symbol, arg_params, aux_params=aux_params,
            data_shapes=data_shapes, ladder=pred.ladder,
            data_dtypes=data_dtypes, ctx=ctx, name="%s-fp32ref" % name,
            bucket_inputs=bucket_inputs)
        hlo_ok = hlo_has_int8_compute if policy.mode == "int8" \
            else hlo_has_int8_tensors
        # NOT seed 0: params initialized from the ubiquitous
        # RandomState(0) share their leading draws with a seed-0 gate
        # stream, so the first gate row ~ the first weight row — a
        # manufactured outlier activation far outside any calibrated
        # range (observed: rel err 0.18 vs 0.01 on decorrelated input)
        rng = _np.random.RandomState(0x5EED)
        rungs = {}
        worst_err = 0.0
        worst_top1 = None

        def _fail(why):
            _QUANT_GATE_FAILURES.inc()
            _obs_events.emit("quantize", kind="gate_failed",
                             model=name, mode=policy.mode, error=why)
            raise QuantizationError(
                "model %r failed the quantization gate: %s"
                % (name, why))

        for b in pred.ladder.batches:
            text = pred.lowered_text(pred.rung_shapes(b))
            _iraudit.audit(
                "quantize", "quantized/b%d" % b, text, model=name,
                dtype_policy=policy.mode,
                budget=len(pred.ladder.batches))
            if not hlo_ok(text):
                _fail("rung %d: no int8 %s in the lowered StableHLO"
                      % (b, "dot/conv compute" if policy.mode == "int8"
                         else "tensors"))
            errs, agree = [], []
            for _ in range(max(1, policy.gate_batches)):
                data = {n: rng.standard_normal(
                    (b,) + tuple(s[1:])).astype(
                        str(pred._data_dtypes[n]))
                    for n, s in pred._data_shapes.items()}
                q_out = pred.predict(data)
                f_out = ref.predict(data)
                for qo, fo in zip(q_out, f_out):
                    qa, fa = qo.asnumpy(), fo.asnumpy()
                    denom = float(_np.abs(fa).max()) or 1.0
                    errs.append(float(_np.abs(qa - fa).max()) / denom)
                    if fa.ndim == 2 and fa.shape[1] > 1:
                        agree.append(float(_np.mean(
                            qa.argmax(axis=1) == fa.argmax(axis=1))))
            err = max(errs)
            top1 = min(agree) if agree else None
            rungs[b] = {"rel_err": round(err, 6),
                        "top1_agreement": top1}
            worst_err = max(worst_err, err)
            if top1 is not None:
                worst_top1 = top1 if worst_top1 is None \
                    else min(worst_top1, top1)
            if err > policy.max_rel_err:
                _fail("rung %d: rel err %.4f > %.4f vs fp32"
                      % (b, err, policy.max_rel_err))
            if policy.min_top1_agreement is not None and \
                    top1 is not None and \
                    top1 < policy.min_top1_agreement:
                _fail("rung %d: top-1 agreement %.4f < %.4f vs fp32"
                      % (b, top1, policy.min_top1_agreement))
        pred.quantization = {
            "mode": policy.mode,
            "calib_sha": report.get("calib_sha"),
            "layers": report.get("layers"),
            "passthrough": report.get("passthrough"),
            "covered": report.get("covered"),
            "total": report.get("total"),
            "policy": policy.to_dict(),
            "gate": {"max_rel_err": round(worst_err, 6),
                     "min_top1_agreement": worst_top1,
                     "rungs": rungs},
        }
        _obs_events.emit(
            "quantize", kind="gate", model=name, mode=policy.mode,
            covered=report.get("covered"), total=report.get("total"),
            max_rel_err=round(worst_err, 6),
            rungs=sorted(rungs),
            calib_sha=(report.get("calib_sha") or "")[:12] or None)

    def load_checkpoint(self, name, prefix, epoch, data_shapes,
                        **kwargs):
        """Load a reference-layout checkpoint (``prefix-symbol.json`` +
        ``prefix-NNNN.params``) straight into the registry."""
        from ..model import load_checkpoint
        sym, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return self.load(name, sym, arg_params, aux_params=aux_params,
                         data_shapes=data_shapes, **kwargs)

    # -- naming ------------------------------------------------------------
    def _resolve(self, name):
        return self._aliases.get(name, name)

    def get(self, name):
        """The predictor for *name* (aliases resolved)."""
        with self._lock:
            pred = self._models.get(self._resolve(name))
        if pred is None:
            raise ServeError("no model %r is loaded (have %s)"
                             % (name, self.names()))
        return pred

    def alias(self, alias, name):
        """Route *alias* to model *name* (repoint allowed — this is
        the traffic-cutover primitive).  On a repoint, the OLD
        target's already-accepted requests are flushed (bounded by
        ``MXNET_SERVE_DRAIN_TIMEOUT``) before returning, so a cutover
        followed by a teardown never drops admitted work."""
        with self._lock:
            target = self._resolve(name)
            if target not in self._models:
                raise ServeError("cannot alias %r to unknown model %r"
                                 % (alias, name))
            if alias in self._models:
                raise ServeError(
                    "%r names a loaded model — unload it before "
                    "turning the name into an alias" % alias)
            old = self._aliases.get(alias)
            self._aliases[alias] = target
            old_batcher = self._batchers.get(old) \
                if old is not None and old != target else None
            old_pred = self._models.get(old) \
                if old is not None and old != target else None
        _obs_events.emit("serve", kind="alias", alias=alias,
                         model=target)
        if old_batcher is not None:
            complete = old_batcher.flush()
            _obs_events.emit("serve", kind="cutover_flush", alias=alias,
                             model=old, complete=bool(complete))
        if old_pred is not None:
            # decode sessions riding the old target are accepted work
            # too: let them finish (bounded), typed-fail the rest and
            # release their pool blocks.  Flush, not close — the old
            # model may still serve through other aliases or its
            # direct name (the predict path's cutover rule)
            self._drain_decoders(old_pred, old, close=False)

    # -- graceful drain / teardown -----------------------------------------
    def _drain_decoders(self, pred, name, timeout=None, drain=True,
                        close=True):
        """Decode half of the never-drop-accepted-work deploy
        contract.  With *close* (unload / load-replace: the model is
        going away) every decode batcher is drained (bounded) and
        closed; sessions finish or typed-fail and their pool blocks
        are released either way.  Without *close* (alias cutover: the
        model may still be reachable through other aliases or its
        direct name) accepted sessions are FLUSHED — they land or
        typed-fail at the deadline — but admissions continue and the
        batcher keeps serving, mirroring the predict path's
        flush-not-close cutover semantics."""
        engines = list(getattr(pred, "_decode_engines", ()))
        for eng in engines:
            for db in list(eng._batchers):
                if not close:
                    complete = db.flush(timeout)
                    _obs_events.emit(
                        "decode", kind="cutover_drain", model=name,
                        batcher=db.name, complete=bool(complete))
                    continue
                if drain:
                    drained = db.drain(timeout)
                    _obs_events.emit(
                        "decode", kind="cutover_drain", model=name,
                        batcher=db.name, complete=bool(drained))
                db.close()

    def drain(self, name, timeout=None):
        """Stop admissions to *name*'s batcher (submits raise a typed
        ServeError) and wait up to *timeout* seconds (default the
        ``MXNET_SERVE_DRAIN_TIMEOUT`` knob) for every accepted request
        to resolve.  The model stays loaded (direct ``predict`` still
        works); ``unload`` completes the teardown.  Returns True when
        the queue fully drained."""
        with self._lock:
            target = self._resolve(name)
            if target not in self._models:
                raise ServeError("no model %r to drain" % name)
            batcher = self._batchers.get(target)
        self._board.transition(target, "draining")
        _DRAINS_TOTAL.inc()
        _obs_events.emit("serve", kind="drain", model=target,
                         mode="drain")
        if batcher is None:
            _obs_events.emit("serve", kind="drain_complete",
                             model=target, mode="drain",
                             waited_requests=0, timed_out=False)
            return True
        drained = batcher.drain(timeout)
        stats = batcher.last_drain_stats or {}
        # the machine-readable drain record (satellite contract): the
        # rolling deploy and the fleet drill gate on "drain completed
        # with zero abandoned work" from this event, not counters
        _obs_events.emit("serve", kind="drain_complete", model=target,
                         mode="drain",
                         waited_requests=stats.get("waited_requests", 0),
                         timed_out=bool(stats.get("timed_out",
                                                  not drained)))
        return drained

    def drain_all(self, timeout=None):
        """Drain every loaded model (the replica's pre-deploy RPC):
        stops admissions model by model and waits (bounded) for the
        accepted requests.  Returns an aggregate machine-readable
        record ``{"models": N, "waited_requests": total,
        "timed_out": any}`` — the fleet's rolling deploy proceeds
        only when ``timed_out`` is False (zero abandoned work)."""
        waited = 0
        timed_out = False
        names = self.names()
        for name in names:
            drained = self.drain(name, timeout)
            with self._lock:
                batcher = self._batchers.get(self._resolve(name))
            stats = (batcher.last_drain_stats or {}) \
                if batcher is not None else {}
            waited += int(stats.get("waited_requests", 0))
            timed_out = timed_out or not drained
        return {"models": len(names), "waited_requests": waited,
                "timed_out": timed_out}

    def resume_all(self):
        """Undo :meth:`drain_all`: reopen admissions on every drained
        model and mark it ready again (the aborted-deploy recovery
        path — a replica whose drain timed out must return to
        service, not shed forever).  Models whose batcher is closed
        or unhealthy are left alone.  Returns the resumed names."""
        resumed = []
        for name in self.names():
            with self._lock:
                target = self._resolve(name)
                batcher = self._batchers.get(target)
            if batcher is not None and not batcher.undrain():
                continue
            if self._board.state(target) == "draining":
                self._board.transition(target, "ready")
            resumed.append(target)
            _obs_events.emit("serve", kind="resume", model=target)
        return resumed

    def unload(self, name, drain=True, timeout=None):
        """Drop a model (or just an alias).  Unloading a model also
        drops every alias pointing at it and closes its batcher.  With
        *drain* (the default) admissions stop first and accepted
        requests get up to *timeout* seconds to finish — a clean
        deploy completes everything it admitted; ``drain=False`` is
        the fast teardown that fails queued futures with a typed
        ServeError."""
        with self._lock:
            if name in self._aliases and name not in self._models:
                del self._aliases[name]
                _obs_events.emit("serve", kind="unalias", alias=name)
                return
            pred = self._models.get(name)
            if pred is None:
                raise ServeError("no model %r to unload" % name)
            batcher = self._batchers.get(name)
        drained = None
        marked_draining = False
        if drain and batcher is not None:
            self._board.transition(name, "draining")
            marked_draining = True
            _DRAINS_TOTAL.inc()
            _obs_events.emit("serve", kind="drain", model=name,
                             mode="unload")
            drained = batcher.drain(timeout)
            stats = batcher.last_drain_stats or {}
            _obs_events.emit(
                "serve", kind="drain_complete", model=name,
                mode="unload",
                waited_requests=stats.get("waited_requests", 0),
                timed_out=bool(stats.get("timed_out", not drained)))
        with self._lock:
            if self._models.get(name) is not pred:
                # lost the race to a concurrent load/unload.  If OUR
                # draining mark is still on the board over a live
                # replacement, lift it — the new model must serve.
                if marked_draining and name in self._models and \
                        self._board.state(name) == "draining":
                    self._board.transition(name, "ready")
                return
            del self._models[name]
            dropped = [a for a, t in self._aliases.items() if t == name]
            for a in dropped:
                del self._aliases[a]
            b = self._batchers.pop(name, None)
            batcher = b or batcher
            _MODELS_GAUGE.dec()
            if getattr(pred, "quantization", None) is not None:
                _QUANT_MODELS_GAUGE.dec()
        if batcher is not None:
            # the board entry dies below — a late dispatcher crash must
            # not resurrect it under the dropped name
            batcher.detach_state_hook()
            batcher.close()
        # decode sessions drain with the model (satellite of the same
        # never-drop-accepted-work contract): with drain=True they
        # finish (bounded) before the typed-fail sweep; either way
        # every pool block is released before the engine closes
        self._drain_decoders(pred, name, timeout, drain=drain)
        for eng in list(getattr(pred, "_decode_engines", ())):
            eng.close()
        self._board.drop(name)
        _obs_events.emit("serve", kind="unload", model=name,
                         aliases_dropped=dropped,
                         **({} if drained is None
                            else {"drained": bool(drained)}))

    def names(self):
        with self._lock:
            return sorted(self._models)

    def aliases(self):
        with self._lock:
            return dict(self._aliases)

    # -- health ------------------------------------------------------------
    def health(self, name=None):
        """The readiness/liveness view.  With *name*: one model's
        state dict — health-board state (batcher unhealthy/draining
        overrides a stale ``ready``), queue depth, dispatcher
        liveness + tick age, restart count, dirty-close flag and
        traffic counters.  Without: ``{model: state dict}`` for every
        loaded model."""
        if name is None:
            with self._lock:
                known = sorted(set(self._models) |
                               set(self._board.snapshot()))
            out = {}
            for n in known:
                try:
                    out[n] = self.health(n)
                except ServeError:
                    # unloaded between the name snapshot and the
                    # per-model read (a deploy racing the probe) —
                    # omit it rather than failing the fleet view
                    continue
            return out
        with self._lock:
            target = self._resolve(name)
            pred = self._models.get(target)
            batcher = self._batchers.get(target)
        state = self._board.state(target)
        if pred is None and state is None:
            raise ServeError("no model %r is loaded (have %s)"
                             % (name, self.names()))
        info = {
            "model": target,
            "state": state or "ready",
            "queue_depth": 0,
            "dispatcher_alive": None,
            "tick_age_s": None,
            "restarts": 0,
            "closed_dirty": False,
            "requests": 0,
            "batches": 0,
            "programs": pred.compile_count if pred is not None else 0,
        }
        if batcher is not None:
            bstate = batcher.health_state()
            if bstate != "ready" and info["state"] == "ready":
                info["state"] = bstate
            info.update(
                queue_depth=batcher.queue_depth,
                dispatcher_alive=batcher.dispatcher_alive(),
                tick_age_s=round(batcher.last_tick_age(), 3),
                restarts=batcher.restart_count,
                closed_dirty=batcher.closed_dirty,
                requests=batcher.request_count,
                batches=batcher.batch_count)
        tuning = getattr(pred, "tuning", None)
        if tuning:
            from ..config import get_env
            info["tuning"] = {
                "workload": tuning.get("workload"),
                "device_kind": tuning.get("device_kind"),
                "config": tuning.get("config"),
                "score": tuning.get("score"),
                "baseline_score": tuning.get("baseline_score"),
                "gain_pct": tuning.get("gain_pct"),
                "source": get_env("MXNET_TUNING_STORE"),
            }
            if batcher is not None:
                # what actually applied after env-wins resolution —
                # an exported env var makes this differ from config
                info["tuning"]["applied"] = {
                    "ladder": list(pred.ladder.batches),
                    "max_wait_ms": batcher._max_wait * 1e3,
                    "max_batch": batcher._max_batch,
                }
        quant = getattr(pred, "quantization", None)
        if quant:
            info["quantization"] = {
                "mode": quant.get("mode"),
                "calib_sha": quant.get("calib_sha"),
                "covered": quant.get("covered"),
                "total": quant.get("total"),
                "layers": quant.get("layers"),
                "gate": quant.get("gate"),
            }
        engines = list(getattr(pred, "_decode_engines", ())) \
            if pred is not None else []
        if engines:
            dbs = [db for eng in engines for db in eng._batchers]
            info["decode"] = {
                "sessions": sum(e.active_sessions for e in engines),
                "kv_blocks_in_use": sum(e.pool.blocks_in_use
                                        for e in engines),
                "kv_blocks_total": sum(e.pool.blocks_total
                                       for e in engines),
                "batchers": [db.health_state() for db in dbs],
                # quarantine-and-rebuild surface: spent/budgeted
                # rebuilds and whether one is in flight right now
                "rebuilds": sum(db.rebuild_count for db in dbs),
                "rebuild_budget": sum(db.rebuild_budget
                                      for db in dbs),
                "rebuilding": any(db.rebuilding for db in dbs),
            }
            if info["state"] == "ready" and \
                    any(db.unhealthy for db in dbs):
                info["state"] = "unhealthy"
            elif info["state"] == "ready" and \
                    info["decode"]["rebuilding"]:
                info["state"] = "rebuilding"
        return info

    def ready(self, name):
        """Readiness probe: does *name* accept new requests?"""
        try:
            return self.health(name)["state"] == "ready"
        except ServeError:
            return False

    def live(self, max_tick_age=5.0):
        """Liveness probe: every dispatcher thread is running and —
        when it has work queued — has ticked within *max_tick_age*
        seconds (a stale tick with pending work is a wedged dispatch,
        not an idle queue)."""
        with self._lock:
            batchers = list(self._batchers.values())
            preds = list(self._models.values())
        for b in batchers:
            if b.unhealthy or not b.dispatcher_alive():
                return False
            if b.queue_depth > 0 and b.last_tick_age() > max_tick_age:
                return False
        for pred in preds:
            for eng in list(getattr(pred, "_decode_engines", ())):
                for db in list(eng._batchers):
                    if db.unhealthy:
                        return False
                    if db.rebuilding:
                        # a quarantine-and-rebuild in flight: the old
                        # dispatcher thread is executing the rebuild,
                        # not ticking — alive, not wedged
                        continue
                    if not db.stopped and not db.dispatcher_alive():
                        return False
                    if db.session_count > 0 and \
                            db.last_tick_age() > max_tick_age:
                        return False
        return True

    # -- request routing ---------------------------------------------------
    def batcher(self, name, **kwargs):
        """Get-or-create the dynamic batcher for a model (aliases
        resolved; knob overrides only apply on creation)."""
        with self._lock:
            target = self._resolve(name)
            if target not in self._models:
                raise ServeError("no model %r is loaded" % name)
            b = self._batchers.get(target)
            if b is None:
                kwargs.setdefault(
                    "on_state",
                    lambda state, _t=target:
                        self._board.transition(_t, state))
                b = DynamicBatcher(self._models[target], name=target,
                                   **kwargs)
                if self._board.state(target) == "draining":
                    # drain() ran before any traffic created a batcher:
                    # the new one must come up with admissions already
                    # stopped, or a post-drain submit would resurrect
                    # the model behind the health surface's back
                    b.drain(timeout=0)
                self._batchers[target] = b
            return b

    def submit(self, name, data, deadline_ms=None):
        """Submit one request to *name*'s dynamic batcher; returns a
        :class:`~mxnet_tpu.serve.batcher.ServeFuture`."""
        return self.batcher(name).submit(data, deadline_ms=deadline_ms)

    def predict(self, name, data, key=None):
        """Direct (unbatched) predict on *name* — bypasses the
        batcher; still padded-bucket, still AOT."""
        return self.get(name).predict(data, key=key)

    def close(self):
        """Unload everything, fast (no drain: batchers closed, queued
        futures failed with a typed ServeError)."""
        for name in self.names():
            self.unload(name, drain=False)


# -- process-wide registry behind the C predict ABI --------------------------

_c_registry = None
_c_registry_lock = _san.lock(label="serve.c_registry")


def c_registry():
    """The process-wide registry the C-ABI predict surface
    (capi_bridge.py MXPredCreate*) routes through."""
    global _c_registry
    if _c_registry is None:
        with _c_registry_lock:
            if _c_registry is None:
                _c_registry = ModelRegistry()
    return _c_registry
