"""ModelRegistry — multi-model serving with a warm compiled-program cache.

The registry is the process's serving control plane (the TVM lesson:
compiled programs are first-class, keyed artifacts, not an implicit
jit side effect):

* ``load`` builds a :class:`CompiledPredictor` and — by default —
  warms every bucket program up front, so the first request is as fast
  as the thousandth;
* ``alias`` gives one compiled model several routable names
  (``"resnet" -> "resnet-v3"`` style traffic cutovers without a
  recompile);
* ``unload`` tears the model, its aliases and its batcher down;
* ``batcher``/``submit`` attach the dynamic batcher to a model by
  name.

Every load/unload/alias is a ``serve`` event, every program build is
counted and blamed (see predictor.py), and the C predict ABI
(capi_bridge.py) is a thin client of the process-wide
:func:`c_registry` instance.
"""

from __future__ import annotations

from .batcher import DynamicBatcher
from .buckets import BucketLadder, ServeError
from .predictor import CompiledPredictor
from .. import sanitizer as _san
from ..observability import events as _obs_events
from ..observability import metrics as _obs_metrics

__all__ = ["ModelRegistry", "c_registry"]

_MODELS_GAUGE = _obs_metrics.gauge(
    "serve_models_loaded",
    "models resident across all serve registries (delta-maintained)")


class ModelRegistry:
    """Named, warm-cached compiled models."""

    def __init__(self):
        self._lock = _san.rlock(label="serve.registry")
        self._models = {}     # name -> CompiledPredictor
        self._aliases = {}    # alias -> canonical name
        self._batchers = {}   # canonical name -> DynamicBatcher
        _san.track(self, ("_models", "_aliases", "_batchers"),
                   label="serve.registry")

    # -- loading -----------------------------------------------------------
    def load(self, name, symbol, arg_params, aux_params=None,
             data_shapes=None, ladder=None, data_dtypes=None, ctx=None,
             warm=True, bucket_inputs=None):
        """Register and (by default) warm-compile a model.  Returns
        the :class:`CompiledPredictor`.  Re-loading a live name
        replaces it atomically (aliases keep pointing at the name)."""

        def _check_not_alias():
            if name in self._aliases:
                raise ServeError(
                    "%r is an alias (for %r) — unalias it before "
                    "loading a model under that name"
                    % (name, self._aliases[name]))

        with self._lock:
            _check_not_alias()      # before paying the warm compiles
        pred = CompiledPredictor(
            symbol, arg_params, aux_params=aux_params,
            data_shapes=data_shapes, ladder=ladder,
            data_dtypes=data_dtypes, ctx=ctx, name=name,
            bucket_inputs=bucket_inputs)
        built = pred.warm() if warm else 0
        with self._lock:
            _check_not_alias()      # racing alias() may have won
            old_batcher = self._batchers.pop(name, None)
            if name not in self._models:
                _MODELS_GAUGE.inc()  # delta: aggregates across registries
            self._models[name] = pred
        if old_batcher is not None:
            old_batcher.close()
        _obs_events.emit("serve", kind="load", model=name,
                         programs=built, warm=bool(warm),
                         buckets=list(pred.ladder.batches))
        return pred

    def load_checkpoint(self, name, prefix, epoch, data_shapes,
                        **kwargs):
        """Load a reference-layout checkpoint (``prefix-symbol.json`` +
        ``prefix-NNNN.params``) straight into the registry."""
        from ..model import load_checkpoint
        sym, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return self.load(name, sym, arg_params, aux_params=aux_params,
                         data_shapes=data_shapes, **kwargs)

    # -- naming ------------------------------------------------------------
    def _resolve(self, name):
        return self._aliases.get(name, name)

    def get(self, name):
        """The predictor for *name* (aliases resolved)."""
        with self._lock:
            pred = self._models.get(self._resolve(name))
        if pred is None:
            raise ServeError("no model %r is loaded (have %s)"
                             % (name, self.names()))
        return pred

    def alias(self, alias, name):
        """Route *alias* to model *name* (repoint allowed — this is
        the traffic-cutover primitive)."""
        with self._lock:
            target = self._resolve(name)
            if target not in self._models:
                raise ServeError("cannot alias %r to unknown model %r"
                                 % (alias, name))
            if alias in self._models:
                raise ServeError(
                    "%r names a loaded model — unload it before "
                    "turning the name into an alias" % alias)
            self._aliases[alias] = target
        _obs_events.emit("serve", kind="alias", alias=alias,
                         model=target)

    def unload(self, name):
        """Drop a model (or just an alias).  Unloading a model also
        drops every alias pointing at it and closes its batcher."""
        with self._lock:
            if name in self._aliases and name not in self._models:
                del self._aliases[name]
                _obs_events.emit("serve", kind="unalias", alias=name)
                return
            if name not in self._models:
                raise ServeError("no model %r to unload" % name)
            del self._models[name]
            dropped = [a for a, t in self._aliases.items() if t == name]
            for a in dropped:
                del self._aliases[a]
            batcher = self._batchers.pop(name, None)
            _MODELS_GAUGE.dec()
        if batcher is not None:
            batcher.close()
        _obs_events.emit("serve", kind="unload", model=name,
                         aliases_dropped=dropped)

    def names(self):
        with self._lock:
            return sorted(self._models)

    def aliases(self):
        with self._lock:
            return dict(self._aliases)

    # -- request routing ---------------------------------------------------
    def batcher(self, name, **kwargs):
        """Get-or-create the dynamic batcher for a model (aliases
        resolved; knob overrides only apply on creation)."""
        with self._lock:
            target = self._resolve(name)
            if target not in self._models:
                raise ServeError("no model %r is loaded" % name)
            b = self._batchers.get(target)
            if b is None:
                b = DynamicBatcher(self._models[target], name=target,
                                   **kwargs)
                self._batchers[target] = b
            return b

    def submit(self, name, data):
        """Submit one request to *name*'s dynamic batcher; returns a
        :class:`~mxnet_tpu.serve.batcher.ServeFuture`."""
        return self.batcher(name).submit(data)

    def predict(self, name, data, key=None):
        """Direct (unbatched) predict on *name* — bypasses the
        batcher; still padded-bucket, still AOT."""
        return self.get(name).predict(data, key=key)

    def close(self):
        """Unload everything (batchers closed, futures failed)."""
        for name in self.names():
            self.unload(name)


# -- process-wide registry behind the C predict ABI --------------------------

_c_registry = None
_c_registry_lock = _san.lock(label="serve.c_registry")


def c_registry():
    """The process-wide registry the C-ABI predict surface
    (capi_bridge.py MXPredCreate*) routes through."""
    global _c_registry
    if _c_registry is None:
        with _c_registry_lock:
            if _c_registry is None:
                _c_registry = ModelRegistry()
    return _c_registry
