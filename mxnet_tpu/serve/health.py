"""Serving health surface — per-model state machine + liveness.

Every served model walks a small state machine the registry drives::

    loading -> warming -> ready -> draining -> (unloaded)
                  \\                   ^
                   \\                  |  (Registry.drain / unload)
                    +--> (load fails, never registered)
    ready -> unhealthy   (dispatcher crashed past its restart budget)

The :class:`HealthBoard` records the state per model, keeps one
delta-maintained gauge per state in the metrics registry (so the
Prometheus exposition carries fleet-level ``serve_models_ready`` /
``_draining`` / ``_unhealthy`` counts without labels), and emits a
``serve`` event (``kind="health"``) on every transition — the state
machine is replayable from ``events.jsonl``.

Readiness vs liveness (the k8s split):

* **ready** — the model accepts new requests: board state ``ready``
  (``Registry.ready(name)``).
* **live** — the serving process is making progress: every batcher's
  dispatcher thread is alive and its liveness tick is fresh
  (``Registry.live()``).  The dispatcher stamps the tick at least
  every ~0.5s even when idle, so a stale tick with work pending means
  a wedged dispatch, not an idle queue.

``Registry.health(name)`` assembles the full per-model view: state,
queue depth, dispatcher liveness/tick age, restart count, dirty-close
flag and traffic counters (see docs/serving.md).
"""

from __future__ import annotations

from .buckets import ServeError
from .. import sanitizer as _san
from ..observability import events as _obs_events
from ..observability import metrics as _obs_metrics

__all__ = ["STATES", "HealthBoard"]

#: the model serving states, in lifecycle order
STATES = ("loading", "warming", "ready", "draining", "unhealthy")

_STATE_GAUGES = {
    s: _obs_metrics.gauge(
        "serve_models_%s" % s,
        "models currently in serving state %r across all registries "
        "(delta-maintained)" % s)
    for s in STATES
}


class HealthBoard:
    """Thread-safe per-model serving state, one per registry."""

    def __init__(self):
        self._lock = _san.lock(label="serve.health")
        self._states = {}
        _san.track(self, ("_states",), label="serve.health")

    def transition(self, model, state):
        """Move *model* to *state* (a member of :data:`STATES`),
        updating the per-state gauges and emitting the ``health``
        event.  Returns the previous state (None for a new model)."""
        if state not in STATES:
            raise ServeError("unknown serving state %r (have %s)"
                             % (state, list(STATES)))
        with self._lock:
            prev = self._states.get(model)
            if prev == state:
                return prev
            self._states[model] = state
            if prev is not None:
                _STATE_GAUGES[prev].dec()
            _STATE_GAUGES[state].inc()
        _obs_events.emit("serve", kind="health", model=model,
                         state=state, prev=prev)
        return prev

    def drop(self, model):
        """Forget *model* (unloaded, or its load failed)."""
        with self._lock:
            prev = self._states.pop(model, None)
            if prev is not None:
                _STATE_GAUGES[prev].dec()
        return prev

    def state(self, model):
        with self._lock:
            return self._states.get(model)

    def snapshot(self):
        with self._lock:
            return dict(self._states)
