"""CompiledPredictor — AOT-compiled inference programs per padding bucket.

Training got its one-donated-program-per-step treatment in PR 1; this
is the inference twin.  A predictor owns:

* the model's pure inference graph (``executor._build_eval`` over the
  bound Symbol, ``training=False``);
* device-committed parameter/aux trees;
* one **ahead-of-time compiled** XLA executable per bucket of the
  :class:`~mxnet_tpu.serve.buckets.BucketLadder` — built via
  ``jit(fn).lower(avals).compile()`` at load/warm time, NEVER in the
  request path.  A compiled executable rejects a mismatched shape with
  a TypeError instead of silently retracing, which is exactly the
  contract serving wants: after warmup the request path cannot compile,
  by construction.

Requests at a natural shape are zero-padded up to their bucket and the
outputs trimmed back (mask-off), proven bit-equal to the unpadded
eager forward in tests/test_serve.py.

Autoregressive decode gets the fused-train-step donation discipline:
:meth:`CompiledPredictor.make_decoder` AOT-compiles a step function
whose KV-cache-style state tree is donated (``donate_argnums``) and
re-donated every step — the cache never copies, and stale host aliases
of donated buffers are poisoned through the graftsan bridge just like
the fused step's weights.
"""

from __future__ import annotations

import time as _time

import numpy as _np

from .buckets import BucketLadder, ServeError
from .. import iraudit as _iraudit
from .. import sanitizer as _san
from ..observability import events as _obs_events
from ..observability import metrics as _obs_metrics
from ..resilience import servechaos as _servechaos

__all__ = ["CompiledPredictor", "DecodeSession"]

# module-level instrument refs (hot path: no registry lookup per call)
_DISPATCH_SECONDS = _obs_metrics.histogram(
    "serve_dispatch_seconds",
    "host-side latency of one compiled-program serve dispatch")
_COMPILES_TOTAL = _obs_metrics.counter(
    "serve_compiles_total",
    "AOT program builds (bucket warmups + decode steps); flat after "
    "warmup or the request path is compiling")
_PADDED_ROWS = _obs_metrics.counter(
    "serve_padded_rows_total",
    "zero-padded rows dispatched (bucket size minus real rows)")
_DEVICE_PUT_ELIDED = _obs_metrics.counter(
    "device_put_elided_total",
    "host->device transfers skipped because the array was already "
    "committed to its target device/sharding (device-resident input)")


def _as_jnp(x):
    """Incoming request array (numpy / NDArray / jax) -> host numpy
    (serving requests originate host-side; the compiled call does the
    single h2d transfer)."""
    data = getattr(x, "_data", None)
    if data is not None:
        return _np.asarray(data)
    return _np.asarray(x)


def _device_resident(arr, dev):
    """AOT-dispatch flavor of ``ndarray._already_placed``: a compiled
    executable has no trace cache, so an input's committedness cannot
    flip a jit cache key here — any live jax array already on *dev*
    may skip the host round trip.  (Compiled-program outputs on CPU
    come back uncommitted, which is exactly the chained-decode case.)
    Deleted/donated buffers fall through to the normal path so the
    real use-after-donate error surfaces at the transfer site."""
    import jax
    if not isinstance(arr, jax.Array):
        return False
    try:
        return arr.devices() == {dev}
    except (RuntimeError, TypeError, AttributeError):
        return False


class CompiledPredictor:
    """AOT-bucketed inference programs for one model.

    Parameters
    ----------
    symbol : Symbol
        The inference graph.
    arg_params : dict name -> array
        Every non-data argument of *symbol*.  Committed to the target
        device at construction.
    aux_params : dict name -> array, optional
        Auxiliary states (BatchNorm running stats, ...).
    data_shapes : dict name -> full shape
        The natural full shape (including a nominal batch dim) of each
        data input — the trailing dims seed :meth:`warm`, and the key
        set defines which symbol arguments are request inputs.
    ladder : BucketLadder, optional
        Defaults to the power-of-two batch ladder.
    data_dtypes : dict name -> dtype, optional
        Request input dtypes (default float32); inputs are cast.
    ctx : Context, optional
        Target device (default: current context).
    name : str
        Model name used in events/errors.
    bucket_inputs : iterable of str, optional
        The data inputs whose leading dim is a batch axis subject to
        the ladder (default: all of them).  Inputs left out are
        **fixed-shape**: requests must match their declared shape
        exactly — no padding, no rung replacement (the C-ABI client
        uses this for multi-input models whose inputs do not share a
        leading dim).
    """

    def __init__(self, symbol, arg_params, aux_params=None,
                 data_shapes=None, ladder=None, data_dtypes=None,
                 ctx=None, name="model", bucket_inputs=None):
        import jax
        import jax.numpy as jnp
        from ..context import current_context
        from ..executor import _build_eval

        if not data_shapes:
            raise ServeError("CompiledPredictor needs data_shapes "
                             "({input name: full shape})")
        self.name = name
        self._symbol = symbol
        self._ctx = ctx or current_context()
        self._dev = self._ctx.jax_device
        self.ladder = ladder or BucketLadder()
        self._data_shapes = {n: tuple(int(d) for d in s)
                             for n, s in data_shapes.items()}
        self._data_dtypes = {
            n: jnp.dtype((data_dtypes or {}).get(n, "float32"))
            for n in self._data_shapes}
        if bucket_inputs is None:
            self._bucket_inputs = frozenset(self._data_shapes)
        else:
            self._bucket_inputs = frozenset(bucket_inputs)
            bad = self._bucket_inputs - set(self._data_shapes)
            if bad:
                raise ServeError(
                    "model %r: bucket_inputs %s are not data inputs"
                    % (name, sorted(bad)))

        arg_names = symbol.list_arguments()
        missing = [n for n in arg_names
                   if n not in self._data_shapes
                   and n not in (arg_params or {})]
        if missing:
            raise ServeError(
                "model %r: arguments %s are neither data inputs nor in "
                "arg_params" % (name, missing))
        unknown = [n for n in self._data_shapes if n not in arg_names]
        if unknown:
            raise ServeError(
                "model %r: data inputs %s are not arguments of the "
                "symbol" % (name, unknown))

        put = lambda a: jax.device_put(
            getattr(a, "_data", None) if getattr(a, "_data", None)
            is not None else jnp.asarray(a), self._dev)
        self._params = {n: put(v) for n, v in (arg_params or {}).items()
                        if n in arg_names and n not in self._data_shapes}
        aux_names = symbol.list_auxiliary_states()
        aux_params = aux_params or {}
        missing_aux = [n for n in aux_names if n not in aux_params]
        if missing_aux:
            raise ServeError("model %r: missing auxiliary states %s"
                             % (name, missing_aux))
        self._aux = {n: put(aux_params[n]) for n in aux_names}
        # fixed base key: inference ops that structurally need rng
        # (none in eval mode for the shipped op set) stay deterministic
        self._key = jax.device_put(jax.random.PRNGKey(0), self._dev)

        self._eval = _build_eval(symbol, False)

        def _predict(params, aux, data, key):
            amap = dict(params)
            amap.update(data)
            outs, _ = self._eval(amap, aux, key)
            return outs

        # the jitted object exists ONLY as the .lower() entry point —
        # its call cache must stay empty (asserted in CI: a non-zero
        # cache size means something traced in the request path)
        self._jit = jax.jit(_predict)
        self._programs = {}        # bucket key -> compiled executable
        self._lock = _san.lock(label="serve.predictor.%s" % name)
        self._compiles = 0
        self._dispatches = 0
        # paged decode engines attached via make_paged_decoder: the
        # registry drains/closes them on unload and alias cutover
        self._decode_engines = []
        # the TuningStore entry the registry attached at load time
        # (None = untuned); DynamicBatcher reads its scalar knobs,
        # health() surfaces it (docs/autotuning.md)
        self.tuning = None
        # quantization report the registry attached at load time
        # (None = fp32): mode, calib sha, per-layer coverage, gate
        # results — surfaced by health() (docs/quantization.md)
        self.quantization = None

    # -- introspection -----------------------------------------------------
    @property
    def compile_count(self):
        """AOT programs built so far (buckets + decoders).  Pinned
        after warmup — a growing count means request-path compiles."""
        return self._compiles

    @property
    def dispatch_count(self):
        return self._dispatches

    def jit_cache_size(self):
        """Size of the traced-call cache of the underlying jit — 0 by
        contract (serving only ever calls AOT executables)."""
        size_of = getattr(self._jit, "_cache_size", None)
        return size_of() if size_of else 0

    def program_keys(self):
        return sorted(self._programs)

    def output_shapes(self, n):
        """Output shapes for a natural batch of *n* rows (trimmed)."""
        shapes = {nm: ((n,) + self._data_shapes[nm][1:])
                  if nm in self._bucket_inputs else self._data_shapes[nm]
                  for nm in self._data_shapes}
        _, out_shapes, _ = self._symbol.infer_shape(**shapes)
        return [tuple(s) for s in out_shapes]

    # -- program cache -----------------------------------------------------
    def _avals(self, shapes):
        import jax
        param_avals = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                       for n, v in self._params.items()}
        aux_avals = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                     for n, v in self._aux.items()}
        data_avals = {n: jax.ShapeDtypeStruct(tuple(s),
                                              self._data_dtypes[n])
                      for n, s in shapes.items()}
        key_aval = jax.ShapeDtypeStruct(self._key.shape,
                                        self._key.dtype)
        return param_avals, aux_avals, data_avals, key_aval

    def _bucket_shapes(self, natural_shapes):
        """{name: padded full shape} for a request's natural shapes —
        batch dims must agree across the bucketed inputs; fixed-shape
        inputs must match their declared shape exactly."""
        batches = {s[0] for n, s in natural_shapes.items()
                   if s and n in self._bucket_inputs}
        if len(batches) > 1:
            raise ServeError(
                "model %r: inputs disagree on batch size (%s)"
                % (self.name, sorted(batches)))
        out = {}
        for n, s in natural_shapes.items():
            if n in self._bucket_inputs:
                out[n] = self.ladder.pad_shape(s)
            elif tuple(s) != self._data_shapes[n]:
                raise ServeError(
                    "model %r fixed-shape input %r: %s does not match "
                    "the declared %s (it is outside bucket_inputs — "
                    "no padding applies)"
                    % (self.name, n, tuple(s), self._data_shapes[n]))
            else:
                out[n] = tuple(s)
        return out

    def ensure_program(self, shapes):
        """Get-or-build the compiled executable for a {name: padded
        full shape} bucket.  Builds are serialized, timed, counted and
        evented (``serve`` category, compile-blame = the bucket key);
        the hit path is one lock-free dict read."""
        key = self.ladder.bucket_key(shapes)
        prog = self._programs.get(key)
        if prog is not None:
            return prog
        with self._lock:
            prog = self._programs.get(key)
            if prog is not None:
                return prog
            # chaos choke point (reject_warm_at): a failed build must
            # propagate as a typed error, never half-register a model
            _servechaos.on_warm(self.name)
            pa, aa, da, ka = self._avals(shapes)
            t0 = _time.perf_counter()
            lowered = self._jit.lower(pa, aa, da, ka)
            if _iraudit.enabled():
                self._audit_rung(key, shapes, lowered.as_text())
            prog = lowered.compile()
            dt = _time.perf_counter() - t0
            self._programs[key] = prog
            self._compiles += 1
            _COMPILES_TOTAL.inc()
            _obs_events.emit(
                "serve", kind="compile", model=self.name,
                bucket=[list(s) for _, s in key],
                seconds=round(dt, 4), programs=len(self._programs))
            return prog

    def _audit_rung(self, key, shapes, text):
        """MXNET_IR_AUDIT hook: register this bucket program with the
        graftir auditor, declaring the rung geometry (GI004 pad-waste:
        the worst natural batch this rung serves is one past the rung
        below) and the ladder size as the program budget (GI005: a
        request-path compile past the warm set is budget growth)."""
        rows = next((shapes[n][0] for n in sorted(self._bucket_inputs)
                     if shapes[n]), None)
        natural = None
        if rows is not None:
            below = [b for b in self.ladder.batches if b < rows]
            natural = (max(below) + 1) if below else 1
        qmode = (self.quantization or {}).get("mode") \
            if isinstance(self.quantization, dict) else None
        _iraudit.audit(
            "serve", "predict/b%s" % rows, text, model=self.name,
            hot_path=True, dtype_policy=qmode,
            bucket_rows=rows, natural_rows=natural,
            budget=len(self.ladder.batches))

    def rung_shapes(self, b):
        """The padded input shapes of the rung that serves a natural
        batch of *b* rows (construction data shapes, bucket-rounded)."""
        return {n: ((self.ladder.batch_for(b),) + tuple(
            self.ladder.round_axis(ax, d)
            for ax, d in enumerate(s[1:], start=1)))
            if n in self._bucket_inputs else s
            for n, s in self._data_shapes.items()}

    def lowered_text(self, shapes):
        """StableHLO of the program for *shapes* (lower only, no
        compile) — what the quantization gate greps for int8 compute
        and costs.py prices."""
        pa, aa, da, ka = self._avals(shapes)
        return self._jit.lower(pa, aa, da, ka).as_text()

    def warm(self, batches=None):
        """Pre-compile one program per batch rung (at the construction
        data shapes) so the request path starts hot, and PRIME each
        with one zero-input execution — first executions pay one-time
        runtime setup that must not land on the first real request.
        Returns the number of programs built."""
        before = self._compiles
        for b in (batches or self.ladder.batches):
            shapes = self.rung_shapes(b)
            prog = self.ensure_program(shapes)
            zeros = {n: _np.zeros(s, self._data_dtypes[n])
                     for n, s in shapes.items()}
            for out in prog(self._params, self._aux, zeros, self._key):
                out.block_until_ready()
        return self._compiles - before

    # -- request path ------------------------------------------------------
    def predict(self, data, key=None):
        """Run one padded-bucket dispatch.

        *data*: {input name: array} (numpy / NDArray / jax), or a
        single array when the model has exactly one input.  An array
        missing the batch dim (ndim == example ndim - 1) counts as a
        single example.  Returns the outputs as NDArrays, trimmed to
        the natural batch size.
        """
        from ..ndarray import NDArray

        if not isinstance(data, dict):
            if len(self._data_shapes) != 1:
                raise ServeError(
                    "model %r has %d inputs — pass a dict"
                    % (self.name, len(self._data_shapes)))
            data = {next(iter(self._data_shapes)): data}
        arrays = {}
        for n in self._data_shapes:
            if n not in data:
                raise ServeError("model %r: request is missing input %r"
                                 % (self.name, n))
            a = _as_jnp(data[n])
            if a.ndim == len(self._data_shapes[n]) - 1:
                a = a[None]    # single example -> batch of one
            if a.ndim != len(self._data_shapes[n]):
                raise ServeError(
                    "model %r input %r: rank %d does not match the "
                    "bound example rank %d"
                    % (self.name, n, a.ndim, len(self._data_shapes[n])))
            arrays[n] = a
        natural = {n: a.shape for n, a in arrays.items()}
        bucketed = [n for n in natural if n in self._bucket_inputs]
        rows = natural[bucketed[0]][0] if bucketed else None
        shapes = self._bucket_shapes(natural)
        prog = self.ensure_program(shapes)

        padded = {}
        for n, a in arrays.items():
            target = shapes[n]
            dt = self._data_dtypes[n]
            if tuple(a.shape) == target and a.dtype == dt:
                padded[n] = a
                continue
            buf = _np.zeros(target, dt)
            buf[tuple(slice(0, s) for s in a.shape)] = a
            padded[n] = buf
        bucket_rows = shapes[bucketed[0]][0] if bucketed else None
        if bucketed and bucket_rows > rows:
            _PADDED_ROWS.inc(bucket_rows - rows)

        t0 = _time.perf_counter()
        with _san.transfer_guard("serve dispatch (%s)" % self.name):
            outs = prog(self._params, self._aux, padded,
                        key if key is not None else self._key)
        _DISPATCH_SECONDS.observe(_time.perf_counter() - t0)
        with self._lock:
            self._dispatches += 1
        trimmed = []
        for o in outs:
            if bucketed and rows != bucket_rows and \
                    getattr(o, "shape", None) and o.shape and \
                    o.shape[0] == bucket_rows:
                o = o[:rows]
            trimmed.append(NDArray(o))
        return trimmed

    # -- parameter refresh -------------------------------------------------
    def set_params(self, arg_params, aux_params=None):
        """Swap in new parameter values WITHOUT recompiling — shapes
        and dtypes must match the compiled avals (a changed shape
        raises; that is a new model, load it under a new name)."""
        import jax
        import jax.numpy as jnp
        for n, v in (arg_params or {}).items():
            if n not in self._params:
                raise ServeError("model %r has no parameter %r"
                                 % (self.name, n))
            cur = self._params[n]
            arr = getattr(v, "_data", None)
            arr = arr if arr is not None else jnp.asarray(v)
            if tuple(arr.shape) != tuple(cur.shape) or \
                    arr.dtype != cur.dtype:
                raise ServeError(
                    "parameter %r changed shape/dtype (%s %s -> %s %s) "
                    "— compiled programs are shape-specialized"
                    % (n, cur.shape, cur.dtype, arr.shape, arr.dtype))
            self._params[n] = jax.device_put(arr, self._dev)
        for n, v in (aux_params or {}).items():
            if n not in self._aux:
                raise ServeError("model %r has no aux state %r"
                                 % (self.name, n))
            arr = getattr(v, "_data", None)
            arr = arr if arr is not None else jnp.asarray(v)
            self._aux[n] = jax.device_put(arr, self._dev)

    # -- autoregressive decode ---------------------------------------------
    def make_decoder(self, step_fn, cache, input_shapes,
                     input_dtypes=None, donate=None, label="decode"):
        """AOT-compile an autoregressive step and return a
        :class:`DecodeSession` that threads its donated state.

        *step_fn(params, cache, inputs, step)* must be pure and return
        ``(outputs, new_cache)`` with ``new_cache`` matching *cache*'s
        tree structure/avals exactly (the donation contract: every
        step's outputs become the next step's donated inputs, like the
        fused train step's weights).  *step* is an int32 scalar the
        session advances — fold it into a key in-graph for stochastic
        decode, never host-side.

        *donate* defaults to ``ops.registry.supports_donation()`` (CPU
        XLA ignores donation and would warn per call); pass ``True``
        to force the declaration — the graftsan donation component
        checks DECLARED donation, so CI exercises the discipline on
        CPU.
        """
        import jax
        import jax.numpy as jnp
        from ..ops.registry import supports_donation

        if donate is None:
            donate = supports_donation()
        cache = jax.tree_util.tree_map(
            lambda a: jax.device_put(
                getattr(a, "_data", None)
                if getattr(a, "_data", None) is not None
                else jnp.asarray(a), self._dev), cache)
        jitted = jax.jit(step_fn,
                         donate_argnums=(1,) if donate else ())
        pa = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
              for n, v in self._params.items()}
        ca = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), cache)
        dtypes = input_dtypes or {}
        ia = {n: jax.ShapeDtypeStruct(
            tuple(int(d) for d in s),
            jnp.dtype(dtypes.get(n, "float32")))
            for n, s in input_shapes.items()}
        step_aval = jax.ShapeDtypeStruct((), jnp.int32)
        t0 = _time.perf_counter()
        lowered = jitted.lower(pa, ca, ia, step_aval)
        # materialize the StableHLO once at build time (tests check the
        # donation declaration) instead of pinning the whole Lowered
        # object for the life of a long-running decode session
        lowered_text = lowered.as_text()
        compiled = lowered.compile()
        del lowered
        dt = _time.perf_counter() - t0
        with self._lock:
            self._compiles += 1
        _COMPILES_TOTAL.inc()
        _obs_events.emit("serve", kind="compile", model=self.name,
                         decoder=label, donated=bool(donate),
                         seconds=round(dt, 4))
        return DecodeSession(self, compiled, cache, ia, donate, label,
                             lowered_text=lowered_text)

    def make_paged_decoder(self, step_fn, prefill_fn=None,
                           token_spec=None, input_spec=None, **kwargs):
        """Build a continuously-batched paged-KV decode engine bound
        to this model: shares its parameters/device/compile
        accounting, and the registry's unload/alias-cutover drains it
        with the model (docs/serving.md "Continuous-batching
        decode").  See :class:`~mxnet_tpu.serve.decode.DecodeEngine`
        for the step/prefill contract and knobs."""
        from .decode import DecodeEngine
        kwargs.setdefault("label", "%s.decode" % self.name)
        return DecodeEngine(step_fn, prefill_fn=prefill_fn,
                            token_spec=token_spec,
                            input_spec=input_spec,
                            predictor=self, **kwargs)


class DecodeSession:
    """One live autoregressive decode: holds the donated cache tree
    and threads it through the compiled step — the serve-side mirror
    of the fused train step's state discipline (cache buffers are
    donated every step and never copied; stale aliases are poisoned
    when the graftsan donation component is on)."""

    def __init__(self, predictor, compiled, cache, input_avals, donate,
                 label, lowered_text=None):
        self._predictor = predictor
        self._compiled = compiled
        self._cache = cache
        self._input_avals = input_avals
        self._donate = donate
        self._label = label
        self._lowered_text = lowered_text
        self._t = 0

    @property
    def step_count(self):
        return self._t

    @property
    def cache(self):
        """The live cache tree (the CURRENT buffers; yesterday's were
        donated — do not keep references across steps)."""
        return self._cache

    def lowered_text(self):
        """StableHLO of the step program (tests check the donation
        declaration survived AOT compilation)."""
        return self._lowered_text or ""

    def step(self, inputs):
        """Run one decode step; returns the step outputs and advances
        the donated cache in place."""
        import jax
        import numpy as np

        pred = self._predictor
        data = {}
        for n, aval in self._input_avals.items():
            if n not in inputs:
                raise ServeError("decode %r: missing input %r"
                                 % (self._label, n))
            raw = inputs[n]
            raw = getattr(raw, "_data", None) \
                if getattr(raw, "_data", None) is not None else raw
            if _device_resident(raw, pred._dev):
                # the previous step's output fed back as this step's
                # input: already committed to the target device — the
                # old np.asarray round trip forced a full d2h readback
                # of every output per token.  Elide it (the PR-11
                # committedness rule) and count the avoided transfer.
                a = raw
                _DEVICE_PUT_ELIDED.inc()
            else:
                a = _as_jnp(raw)
            if tuple(a.shape) != tuple(aval.shape):
                raise ServeError(
                    "decode %r input %r: shape %s does not match the "
                    "compiled %s (decode programs are fixed-shape; "
                    "pad upstream)" % (self._label, n,
                                       tuple(a.shape),
                                       tuple(aval.shape)))
            data[n] = a.astype(aval.dtype) if a.dtype != aval.dtype \
                else a
        old_leaves = jax.tree_util.tree_leaves(self._cache) \
            if self._donate and _san.enabled("donation") else None
        t0 = _time.perf_counter()
        with _san.transfer_guard("serve decode step (%s)" % self._label):
            outs, new_cache = self._compiled(
                pred._params, self._cache, data, np.int32(self._t))
        _DISPATCH_SECONDS.observe(_time.perf_counter() - t0)
        with pred._lock:
            pred._dispatches += 1
        self._cache = new_cache
        self._t += 1
        if old_leaves is not None:
            # every framework-visible container now points at the new
            # buffers; anything still aliasing the donated cache is
            # stale — same poison rule as the fused step's weights
            _san.poison_donated(
                old_leaves, "serve decode step %d (%s)"
                % (self._t - 1, self._label))
        return outs
