"""Continuous-batching LLM decode over the paged KV pool.

The dense :class:`~mxnet_tpu.serve.predictor.DecodeSession` decodes
one sequence per program: N concurrent sessions pay N dispatches per
token and N worst-case caches.  This module makes decode a served,
continuously-batched workload:

* :class:`DecodeEngine` — AOT-compiles one **decode-tick** program per
  session-count rung of a :class:`~mxnet_tpu.serve.buckets.BucketLadder`
  and one **prefill** program per sequence rung, all against a shared
  :class:`~mxnet_tpu.serve.kvpool.KVPool`.  The tick program gathers
  each session's dense cache view through its block table, runs the
  model's step, and scatters back only the block the new token landed
  in; the pool state is donated every call, fused-train-step style.
  Programs are built at construction (warm) — the request path cannot
  compile, by construction.
* :class:`PagedSession` — one live decode: host-side block table,
  position cursor and delivered-token stream.
* :class:`DecodeBatcher` — the continuous-batching tick loop (the
  DynamicBatcher's coalescing/deadline/cancel discipline applied to
  sessions): sessions join and leave *between* ticks, one dispatch +
  one device->host readback serves every active session's next token.
  Prefill dispatches run between ticks through their own bucketed
  programs, so a long prompt costs one dispatch instead of stalling
  the tick loop for L rounds.
* :class:`SpeculativeDecoder` — (stretch, opt-in) a small draft
  engine proposes K tokens; the target verifies all K in ONE batched
  verify dispatch, accepting the matched prefix plus one corrected
  token.  Greedy speculative decode is bit-equal to plain greedy
  decode, because rejected cache positions are beyond-position
  garbage the step contract already ignores.

Step contract (what a model plugs in)::

    step_fn(params, view, inputs, pos) -> (out, new_view)

* ``view``: pytree of dense per-session cache views, leaves
  ``(S, padded_len) + per_token_shape`` gathered from the pool;
* ``inputs``: ``{name: (S,) + input_shape}`` this tick's per-session
  inputs; ``pos``: ``(S,) int32`` tokens already cached per session;
* the step must write **exactly at position** ``pos`` (one token per
  tick) and must mask everything at positions ``>= pos+1`` out of its
  outputs — positions beyond a session's cursor hold co-tenant
  garbage by design (that is what makes block sharing safe; the CI
  drill proves stream bit-equality with the null block poisoned).

    prefill_fn(params, inputs, length) -> view

* ``inputs``: ``{name: (1, Lr) + input_shape}`` the prompt *prefix*
  (everything but its last token), zero-padded to the sequence rung
  ``Lr``; ``length`` is the real prefix length; the returned view
  (leaves ``(1, Lr) + per_token_shape``) is scattered into the
  session's blocks.  The prompt's last token then rides the first
  regular decode tick, so every emitted token comes from the same
  tick program — the bit-equality anchor.

Fault tolerance (PR: streaming decode fault tolerance): every
session rides an idempotent append-only :class:`DecodeJournal`
record — identity ``(client, session_seq, incarnation)``, prompt,
sampling config, params sha and the accepted-token log — so greedy
decode is deterministically resumable from prompt + accepted tokens
via ONE re-prefill plus replayed ticks (delivery suppressed, each
replayed output bit-checked against the journal).  A tick-loop crash
no longer marks the batcher unhealthy forever: the suspect pool is
quarantined, a fresh same-shape :class:`~mxnet_tpu.serve.kvpool.KVPool`
is swapped in against the already-warm programs (zero new compiles,
asserted) and journaled sessions are re-admitted — bounded by
``MXNET_SERVE_DECODE_REBUILDS``, past which the batcher degrades to
the old unhealthy typed-fail behavior.  See docs/serving.md ("Decode
fault tolerance").

See docs/serving.md ("Continuous-batching decode") for the pool
layout, scheduling and knob table.
"""

from __future__ import annotations

import collections
import logging
import time as _time

import numpy as _np

from .buckets import (BucketLadder, DeadlineExceededError,
                      RequestCancelled, ServeError)
from .kvpool import KVPool, KVPoolExhausted
from .. import iraudit as _iraudit
from ..resilience import servechaos as _servechaos
from .. import sanitizer as _san
from ..observability import events as _obs_events
from ..observability import metrics as _obs_metrics

__all__ = ["DecodeEngine", "PagedSession", "DecodeBatcher",
           "DecodeJournal", "SpeculativeDecoder"]

log = logging.getLogger(__name__)

# module-level instrument refs (hot path discipline, see metrics.py);
# serve_dispatch_seconds / serve_compiles_total are the predictor's
# instruments — get-or-create returns the shared ones
_ACTIVE_SESSIONS = _obs_metrics.gauge(
    "serve_decode_active_sessions",
    "live paged decode sessions (admitted and not yet finished/"
    "failed/cancelled) across all decode engines (delta-maintained)")
_DECODE_STEPS = _obs_metrics.counter(
    "serve_decode_steps_total",
    "batched decode-tick dispatches (one serves every active "
    "session's next token)")
_DECODE_TOKENS = _obs_metrics.counter(
    "serve_decode_tokens_total",
    "tokens delivered to decode sessions")
_TOKEN_SECONDS = _obs_metrics.histogram(
    "serve_decode_token_seconds",
    "per-token latency: time between successive token deliveries of "
    "a session (first token: admission to delivery)")
_DISPATCH_SECONDS = _obs_metrics.histogram(
    "serve_dispatch_seconds",
    "host-side latency of one compiled-program serve dispatch")
_COMPILES_TOTAL = _obs_metrics.counter(
    "serve_compiles_total",
    "AOT program builds (bucket warmups + decode steps); flat after "
    "warmup or the request path is compiling")
_FAILOVERS_TOTAL = _obs_metrics.counter(
    "serve_decode_failovers_total",
    "decode sessions re-opened on another replica after their "
    "replica died / ejected / drained (router-side journal resume)")
_REBUILDS_TOTAL = _obs_metrics.counter(
    "serve_decode_rebuilds_total",
    "decode pool quarantine-and-rebuild cycles after a tick-loop "
    "crash (bounded by MXNET_SERVE_DECODE_REBUILDS)")
_RESUMED_TOTAL = _obs_metrics.counter(
    "serve_decode_resumed_sessions_total",
    "journaled decode sessions re-admitted via re-prefill + replayed "
    "ticks (in-process rebuilds and router-side failovers)")


def _ceil_div(a, b):
    return -(-int(a) // int(b))


def _token_bytes(out):
    """Canonical byte identity of one step-output tree — the journal
    replay bit-equality check (and the speculative accept test)."""
    import jax
    return tuple(_np.asarray(leaf).tobytes()
                 for leaf in jax.tree_util.tree_leaves(out))


class JournalRecord:
    """One session's journal entry: identity, everything needed to
    re-prefill, and the accepted-token log."""

    __slots__ = ("client", "seq", "incarnation", "prompt", "length",
                 "max_new_tokens", "sampling", "params_sha", "tokens",
                 "closed", "reason")

    def __init__(self, client, seq, incarnation, prompt, length,
                 max_new_tokens, sampling, params_sha):
        self.client = client
        self.seq = int(seq)
        self.incarnation = int(incarnation)
        self.prompt = prompt          # {name: (L,)+shape} host arrays
        self.length = int(length)
        self.max_new_tokens = max_new_tokens
        self.sampling = sampling      # e.g. {"mode": "greedy"}
        self.params_sha = params_sha
        self.tokens = []              # accepted host output trees
        self.closed = False
        self.reason = None

    @property
    def key(self):
        return (self.client, self.seq)


class DecodeJournal:
    """Idempotent append-only record of decode sessions — the resume
    source of truth.

    Each record carries the session identity ``(client, session_seq,
    incarnation)``, the normalized prompt, the sampling config, the
    engine's params sha and the accepted-token log.  ``append`` is
    idempotent by token index (a replayed tick re-appending token *i*
    is a no-op; a gap is a bug and raises), so crash-retried writers
    never double-log.  Greedy decode is deterministically resumable
    from a record: one re-prefill of the prompt prefix plus replayed
    ticks feeding the journaled tokens reproduces the interrupted
    stream bit-equal (proven against
    ``test_utils.dense_decode_reference``).

    Used in-process by :class:`DecodeEngine` (direct ``DecodeBatcher``
    sessions, key ``("local", sid, 0)``) and router-side for fleet
    sessions (the router journals what the replica streamed back, and
    re-opens elsewhere from it on failover).  Closed records are kept
    for a bounded window so late duplicate RPCs can still be answered
    from the log."""

    def __init__(self, label="journal", keep_closed=64):
        self.label = label
        self._keep_closed = int(keep_closed)
        self._lock = _san.lock(label="serve.decode.journal.%s" % label)
        self._records = collections.OrderedDict()
        _san.track(self, ("_records",),
                   label="serve.decode.journal.%s" % label)

    def open(self, client, seq, incarnation, prompt, length,
             max_new_tokens=None, sampling=None, params_sha=None):
        """Open (or re-open) a record — idempotent on ``(client,
        seq)``: a retried OPEN returns the existing record; a resume
        under a bumped *incarnation* updates the stamp and keeps the
        accepted-token log."""
        key = (client, int(seq))
        with self._lock:
            rec = self._records.get(key)
            if rec is not None:
                if int(incarnation) > rec.incarnation:
                    rec.incarnation = int(incarnation)
                return rec
            rec = JournalRecord(client, seq, incarnation, prompt,
                                length, max_new_tokens,
                                sampling or {"mode": "greedy"},
                                params_sha)
            self._records[key] = rec
            self._trim_locked()
            return rec

    def append(self, key, index, token):
        """Log accepted token *index* — idempotent: re-appending an
        already-logged index is a no-op, a gap raises (accepted
        tokens are never lost, so a gap means the caller skipped
        one)."""
        with self._lock:
            rec = self._records.get((key[0], int(key[1])))
            if rec is None or rec.closed:
                return
            index = int(index)
            if index < len(rec.tokens):
                return            # duplicate (replayed tick) — no-op
            if index > len(rec.tokens):
                raise ServeError(
                    "decode journal %r: token %d appended with %d "
                    "logged — the accepted-token log has a gap"
                    % (self.label, index, len(rec.tokens)))
            rec.tokens.append(token)

    def record(self, key):
        with self._lock:
            return self._records.get((key[0], int(key[1])))

    def tokens(self, key):
        """The accepted-token log (a copy) — the replay source."""
        with self._lock:
            rec = self._records.get((key[0], int(key[1])))
            return list(rec.tokens) if rec is not None else []

    def close(self, key, reason):
        """Mark a record terminal (idempotent).  Kept for the closed
        window, then trimmed."""
        with self._lock:
            rec = self._records.get((key[0], int(key[1])))
            if rec is None or rec.closed:
                return
            rec.closed = True
            rec.reason = reason
            self._trim_locked()

    def live_records(self):
        """Records not yet terminal — what a rebuild/failover must
        re-admit (or fail typed)."""
        with self._lock:
            return [r for r in self._records.values() if not r.closed]

    def _trim_locked(self):
        closed = [k for k, r in self._records.items() if r.closed]
        while len(closed) > self._keep_closed:
            self._records.pop(closed.pop(0), None)


class PagedSession:
    """One live paged decode: block table, position cursor, and the
    delivered token stream.  Engine-owned fields (``pos``, ``blocks``,
    ``table``, ``pending_input``) are mutated only under the engine
    lock by the tick/prefill path; readers use the delivery methods,
    which synchronize on the session's own condition."""

    _NEXT_SID = [0]
    _SID_LOCK = _san.lock(label="serve.decode.sid")

    def __init__(self, engine, prompt, length, blocks, table,
                 max_new_tokens, stop_fn, deadline):
        with self._SID_LOCK:
            self._NEXT_SID[0] += 1
            self.sid = self._NEXT_SID[0]
        self._engine = engine
        self.prompt = prompt          # {name: (L,) + input_shape} host
        self.length = int(length)
        self.blocks = blocks          # pool block ids, growth in ticks
        self.table = table            # np int32 (max_blocks,)
        self.pos = 0                  # set by prefill; tokens cached
        self.pending_input = None     # next tick's {name: host array}
        self.max_new_tokens = max_new_tokens
        self.stop_fn = stop_fn
        self._deadline = deadline     # monotonic; bounds time-to-join
        self.journal_key = None       # (client, seq) — set by admit
        self._replay = collections.deque()  # journaled outs to replay
        self._base = 0                # tokens emitted before a resume
                                      # (wire resume: delivery starts
                                      # fresh, budgets count the total)
        self._cond = _san.condition(
            label="serve.decode.session%d" % self.sid)
        self._outputs = []
        self._stamps = []             # monotonic delivery stamp/token
        self._queue = collections.deque()
        self._done = False
        self._released = False
        self._cancel = False
        self._error = None
        self.finish_reason = None
        self._t_enq = _time.monotonic()
        self._t_last = None
        _san.track(self, ("_outputs", "_queue", "_done", "_released",
                          "_cancel", "_error"),
                   label="serve.decode.session%d" % self.sid)

    # -- caller side --------------------------------------------------------
    def done(self):
        with self._cond:
            return self._done

    @property
    def error(self):
        with self._cond:
            return self._error

    @property
    def token_count(self):
        with self._cond:
            return len(self._outputs)

    def outputs(self):
        """Everything delivered so far — readable even after a typed
        mid-stream failure (accepted steps are never lost)."""
        with self._cond:
            return list(self._outputs)

    def stamps(self):
        """Monotonic delivery timestamp per token (open-loop latency
        accounting: per-token resolve stamps, no coordinated
        omission)."""
        with self._cond:
            return list(self._stamps)

    def next_output(self, timeout=None):
        """Block for the next token.  Raises the session's typed
        error after a failure, ``StopIteration`` after a clean
        finish, ``TimeoutError`` on *timeout*."""
        deadline = None if timeout is None \
            else _time.monotonic() + timeout
        with self._cond:
            while not self._queue and not self._done:
                remaining = None if deadline is None \
                    else deadline - _time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        "decode session %d: no token after %ss"
                        % (self.sid, timeout))
                self._cond.wait(remaining)
            if self._queue:
                return self._queue.popleft()
            if self._error is not None:
                raise self._error
            raise StopIteration("decode session %d finished (%s)"
                                % (self.sid, self.finish_reason))

    def output_at(self, i, timeout=None):
        """Non-consuming read of delivered token *i* (0-based in this
        session's delivered stream): blocks until it exists, the
        session finishes short of it, or *timeout*.  The wire
        DECODE_NEXT dedup path — a retried index is answered from the
        retained stream, never re-decoded.  Raises the typed error
        after a failure, ``StopIteration`` when the stream finished
        before index *i*, ``TimeoutError`` on *timeout*."""
        i = int(i)
        deadline = None if timeout is None \
            else _time.monotonic() + timeout
        with self._cond:
            while True:
                if len(self._outputs) > i:
                    return self._outputs[i]
                if self._done:
                    if self._error is not None:
                        raise self._error
                    raise StopIteration(
                        "decode session %d finished (%s) at %d "
                        "token(s)" % (self.sid, self.finish_reason,
                                      len(self._outputs)))
                remaining = None if deadline is None \
                    else deadline - _time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        "decode session %d: token %d not delivered "
                        "after %ss" % (self.sid, i, timeout))
                self._cond.wait(remaining)

    def result(self, timeout=None):
        """Wait for the session to finish; returns the full output
        stream, or raises the typed failure."""
        deadline = None if timeout is None \
            else _time.monotonic() + timeout
        with self._cond:
            while not self._done:
                remaining = None if deadline is None \
                    else deadline - _time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        "decode session %d still live after %ss"
                        % (self.sid, timeout))
                self._cond.wait(remaining)
            if self._error is not None:
                raise self._error
            return list(self._outputs)

    def cancel(self):
        """Abandon the session.  The engine releases its blocks at
        the next tick boundary; pending readers get a typed
        :class:`RequestCancelled`.  Tokens already delivered stay
        readable via :meth:`outputs`."""
        with self._cond:
            if self._done:
                return False
            self._cancel = True
        return True

    @property
    def cancelled(self):
        with self._cond:
            return self._cancel

    @property
    def resuming(self):
        """True while journaled tokens are still being replayed (the
        session is catching its cache up; delivery is suppressed)."""
        return bool(self._replay)

    # -- engine side --------------------------------------------------------
    def _deliver(self, out, now):
        with self._cond:
            _TOKEN_SECONDS.observe(
                now - (self._t_last if self._t_last is not None
                       else self._t_enq))
            self._t_last = now
            self._outputs.append(out)
            self._stamps.append(now)
            self._queue.append(out)
            self._cond.notify_all()


class DecodeEngine:
    """AOT tick/prefill programs over one shared :class:`KVPool`.

    Parameters
    ----------
    step_fn, prefill_fn : callables
        The model's decode step / prompt prefill (module docstring
        contract).  ``prefill_fn`` may be None when every prompt has
        length 1 (pure generation).
    token_spec : pytree of jax.ShapeDtypeStruct
        One token's cache slice per leaf (the pool layout).
    input_spec : dict name -> jax.ShapeDtypeStruct
        Per-session, per-tick inputs (e.g. the previous token id).
    params : pytree of arrays, optional
        Model parameters, committed to the pool's device.  Defaults
        to *predictor*'s parameters when attached.
    predictor : CompiledPredictor, optional
        Attach for registry lifecycle (unload/cutover drain this
        engine) and shared compile accounting.
    max_len : int
        Longest sequence a session may reach; rounded up to a whole
        number of blocks (:attr:`padded_len` — the dense-view length
        every step program sees).
    session_rungs : sequence of int, optional
        Session-count rungs of the tick ladder (one AOT program
        each).  Default: the autotuner's winning ladder for
        ``(label, device, "decode")`` when ``MXNET_TUNING_STORE``
        names a store holding one, else ``(1, 2, 4, 8, 16)``.
    prefill_rungs : sequence of int, optional
        Sequence rungs of the prefill programs; each must be a
        multiple of the block size.  Default: block-size
        powers-of-two up to :attr:`padded_len`.
    next_input_fn : callable, optional
        Maps a delivered (host) step output to the next tick's input
        dict.  Default: identity when the output tree matches
        ``input_spec``.
    spec_k : int
        When > 0, also compile the K-token speculative **verify**
        program (see :class:`SpeculativeDecoder`).  Off by default —
        speculative decode is opt-in.
    donate : bool, optional
        Donate the pool state to every program call (default
        ``ops.registry.supports_donation()``; pass True to force the
        declaration — CPU CI checks declared donation).
    """

    def __init__(self, step_fn, prefill_fn=None, token_spec=None,
                 input_spec=None, params=None, predictor=None,
                 max_len=None, block_size=None, num_blocks=None,
                 session_rungs=None, prefill_rungs=None,
                 next_input_fn=None, spec_k=0, donate=None,
                 device=None, label="decode", warm=True):
        import jax
        import jax.numpy as jnp
        from ..config import resolve_env
        from ..ops.registry import supports_donation

        if step_fn is None or token_spec is None or not input_spec:
            raise ServeError("DecodeEngine needs step_fn, token_spec "
                             "and input_spec")
        if max_len is None:
            raise ServeError("DecodeEngine needs max_len (the longest "
                             "sequence a session may reach)")
        self.label = label
        # tuned-store consultation (docs/autotuning.md): an explicit
        # constructor argument always wins; a knob left None falls to
        # exported env > tuned entry keyed (label, device, "decode") >
        # registered default
        self.tuning = self._tuning_entry(label)
        tcfg = (self.tuning or {}).get("config") or {}
        if block_size is None:
            block_size = resolve_env(
                "MXNET_SERVE_KV_BLOCK_SIZE",
                tcfg.get("MXNET_SERVE_KV_BLOCK_SIZE"))
        if num_blocks is None:
            num_blocks = resolve_env(
                "MXNET_SERVE_KV_BLOCKS",
                tcfg.get("MXNET_SERVE_KV_BLOCKS"))
        if session_rungs is None:
            session_rungs = tuple(tcfg.get("ladder")
                                  or (1, 2, 4, 8, 16))
        self._step_fn = step_fn
        self._prefill_fn = prefill_fn
        self._predictor = predictor
        if predictor is not None and device is None:
            device = predictor._dev
        self._pool = KVPool(token_spec, num_blocks=num_blocks,
                            block_size=block_size, device=device)
        bs = self._pool.block_size
        self.block_size = bs
        self.padded_len = _ceil_div(max_len, bs) * bs
        self.max_blocks = self.padded_len // bs
        if self.max_blocks > self._pool.blocks_total:
            raise ServeError(
                "a full-length session needs %d blocks but the pool "
                "only has %d allocatable — grow MXNET_SERVE_KV_BLOCKS "
                "or shrink max_len" % (self.max_blocks,
                                       self._pool.blocks_total))
        self.ladder = BucketLadder(batches=session_rungs)
        if prefill_rungs is None:
            rungs, r = [], bs
            while r < self.padded_len:
                rungs.append(r)
                r *= 2
            rungs.append(self.padded_len)
            prefill_rungs = rungs
        self.prefill_rungs = tuple(sorted({int(r) for r in
                                           prefill_rungs}))
        for r in self.prefill_rungs:
            if r < bs or r % bs or r > self.padded_len:
                raise ServeError(
                    "prefill rung %d must be a multiple of the block "
                    "size %d within padded_len %d"
                    % (r, bs, self.padded_len))
        if self.prefill_rungs and \
                self.prefill_rungs[-1] != self.padded_len:
            self.prefill_rungs = self.prefill_rungs + (self.padded_len,)
        self._input_spec = {
            n: jax.ShapeDtypeStruct(tuple(int(d) for d in s.shape),
                                    jnp.dtype(s.dtype))
            for n, s in input_spec.items()}
        self._next_input_fn = next_input_fn
        self.spec_k = int(spec_k)
        if donate is None:
            donate = supports_donation()
        self._donate = bool(donate)
        if params is None:
            if predictor is None:
                raise ServeError("DecodeEngine needs params (or an "
                                 "attached predictor to take them "
                                 "from)")
            params = predictor._params
        put = lambda a: jax.device_put(
            getattr(a, "_data", None)
            if getattr(a, "_data", None) is not None
            else jnp.asarray(a), self._pool.device)
        self._params = jax.tree_util.tree_map(put, params)

        self._lock = _san.lock(label="serve.decode.%s" % label)
        self._tick_progs = {}
        self._tick_text = {}
        self._prefill_progs = {}
        self._prefill_text = {}
        self._verify_prog = None
        self._verify_text = None
        self._compiles = 0
        self._dispatches = 0
        self._live = []               # admitted, not yet released
        self._batchers = []
        self._closed = False
        self._journal = DecodeJournal(label)
        self._params_sha_cache = None
        self._rebuilds = 0            # pool quarantine-and-rebuilds
        _san.track(self, ("_tick_progs", "_prefill_progs", "_compiles",
                          "_dispatches", "_live", "_closed"),
                   label="serve.decode.%s" % label)
        if predictor is not None:
            predictor._decode_engines.append(self)
        if warm:
            self.warm()

    @staticmethod
    def _tuning_entry(label, workload="decode"):
        from ..autotune.store import lookup
        return lookup(label, workload)

    # -- introspection -------------------------------------------------------
    @property
    def compile_count(self):
        return self._compiles

    @property
    def dispatch_count(self):
        with self._lock:
            return self._dispatches

    @property
    def pool(self):
        return self._pool

    @property
    def active_sessions(self):
        with self._lock:
            return len(self._live)

    @property
    def journal(self):
        """The engine's in-process :class:`DecodeJournal`."""
        return self._journal

    @property
    def rebuild_count(self):
        with self._lock:
            return self._rebuilds

    def params_sha(self):
        """sha256 over the host bytes of every parameter leaf
        (computed once, cached) — the journal's model-identity stamp:
        a resume onto drifted params would not be bit-equal, so the
        caller can refuse it up front."""
        if self._params_sha_cache is None:
            import hashlib
            import jax
            h = hashlib.sha256()
            for leaf in jax.tree_util.tree_leaves(self._params):
                h.update(_np.asarray(leaf).tobytes())
            self._params_sha_cache = h.hexdigest()[:16]
        return self._params_sha_cache

    def tick_lowered_text(self, rung):
        return self._tick_text.get(int(rung), "")

    def prefill_lowered_text(self, rung):
        return self._prefill_text.get(int(rung), "")

    def verify_lowered_text(self):
        return self._verify_text or ""

    def lower_tick_text(self, S):
        """StableHLO of the S-session tick (lower only, no compile) —
        the graftir representative-set path on CPU avals."""
        return self._lower_tick(int(S)).as_text()

    def lower_prefill_text(self, Lr):
        """StableHLO of the Lr-token prefill (lower only)."""
        if self._prefill_fn is None:
            raise ServeError("decode %r has no prefill_fn" % self.label)
        return self._lower_prefill(int(Lr)).as_text()

    # -- program builders ----------------------------------------------------
    def _count_compile(self, kind, key, seconds):
        self._compiles += 1
        _COMPILES_TOTAL.inc()
        if self._predictor is not None:
            with self._predictor._lock:
                self._predictor._compiles += 1
        _obs_events.emit("serve", kind="compile", model=self.label,
                         decoder=kind, rung=key,
                         donated=self._donate,
                         seconds=round(seconds, 4))

    def _pool_avals(self):
        import jax
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            self._pool.arrays)

    def _param_avals(self):
        import jax
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            self._params)

    def _lower_tick(self, S):
        """Lower (no compile) the S-session tick program."""
        import jax
        import jax.numpy as jnp
        bs, nb, L = self.block_size, self.max_blocks, self.padded_len
        step_fn = self._step_fn

        def _tick(params, pool, table, pos, inputs):
            idx = jnp.arange(S)
            view = jax.tree_util.tree_map(
                lambda p: p[table].reshape((S, L) + p.shape[2:]), pool)
            out, new_view = step_fn(params, view, inputs, pos)
            blk = pos // bs                      # (S,) block-in-seq
            blk_ids = table[idx, blk]            # (S,) pool block ids
            def scat(p, nv):
                nvb = nv.reshape((S, nb, bs) + p.shape[2:])
                return p.at[blk_ids].set(nvb[idx, blk])
            new_pool = jax.tree_util.tree_map(scat, pool, new_view)
            return out, new_pool

        jitted = jax.jit(_tick, donate_argnums=(1,)
                         if self._donate else ())
        pa, ka = self._param_avals(), self._pool_avals()
        ta = jax.ShapeDtypeStruct((S, nb), jnp.int32)
        sa = jax.ShapeDtypeStruct((S,), jnp.int32)
        ia = {n: jax.ShapeDtypeStruct((S,) + sp.shape, sp.dtype)
              for n, sp in self._input_spec.items()}
        return jitted.lower(pa, ka, ta, sa, ia)

    def _audit(self, kind, rung, text):
        """MXNET_IR_AUDIT hook: one registration per decode program
        (the pool is the donated input; session rungs + prefill rungs
        + optional verify are the program budget)."""
        import jax
        budget = len(self.ladder.batches) + \
            (len(self.prefill_rungs) if self._prefill_fn else 0) + \
            (1 if self.spec_k > 0 else 0)
        n_pool = len(jax.tree_util.tree_leaves(self._pool.arrays))
        _iraudit.audit(
            "decode", "%s/%s" % (kind, rung), text, model=self.label,
            hot_path=True, donated=n_pool if self._donate else None,
            budget=budget)

    def _build_tick(self, S):
        t0 = _time.perf_counter()
        lowered = self._lower_tick(S)
        text = lowered.as_text()
        if _iraudit.enabled():
            self._audit("tick", "S%d" % S, text)
        prog = lowered.compile()
        del lowered
        # caller (warm) holds self._lock for the whole build pass
        self._tick_progs[S] = prog  # graftlint: disable=JG010
        self._tick_text[S] = text
        self._count_compile("tick", S, _time.perf_counter() - t0)
        return prog

    def _lower_prefill(self, Lr):
        """Lower (no compile) the Lr-token prefill program."""
        import jax
        import jax.numpy as jnp
        bs, nb = self.block_size, self.max_blocks
        nbr = Lr // bs
        prefill_fn = self._prefill_fn

        # prefill_fn returns leaves (1, Lr) + token_shape; drop the
        # session axis, split into whole blocks and scatter them into
        # the session's table (tail entries point at the null block —
        # their garbage lands where no session reads)
        def _prefill(params, pool, table, inputs, length):
            view = prefill_fn(params, inputs, length)
            def scat(p, v):
                vb = v[0].reshape((nbr, bs) + p.shape[2:])
                return p.at[table[:nbr]].set(vb)
            return jax.tree_util.tree_map(scat, pool, view)

        jitted = jax.jit(_prefill, donate_argnums=(1,)
                         if self._donate else ())
        pa, ka = self._param_avals(), self._pool_avals()
        ta = jax.ShapeDtypeStruct((nb,), jnp.int32)
        ia = {n: jax.ShapeDtypeStruct((1, Lr) + sp.shape, sp.dtype)
              for n, sp in self._input_spec.items()}
        la = jax.ShapeDtypeStruct((), jnp.int32)
        return jitted.lower(pa, ka, ta, ia, la)

    def _build_prefill(self, Lr):
        t0 = _time.perf_counter()
        lowered = self._lower_prefill(Lr)
        text = lowered.as_text()
        if _iraudit.enabled():
            self._audit("prefill", "L%d" % Lr, text)
        prog = lowered.compile()
        del lowered
        # caller (warm) holds self._lock for the whole build pass
        self._prefill_progs[Lr] = prog  # graftlint: disable=JG010
        self._prefill_text[Lr] = text
        self._count_compile("prefill", Lr, _time.perf_counter() - t0)
        return prog

    def _build_verify(self):
        import jax
        import jax.numpy as jnp
        bs, nb, L, K = (self.block_size, self.max_blocks,
                        self.padded_len, self.spec_k)
        step_fn = self._step_fn

        def _verify(params, pool, table, pos0, inputs):
            view = jax.tree_util.tree_map(
                lambda p: p[table].reshape((1, L) + p.shape[2:]), pool)

            def body(carry, xs):
                toks, i = xs
                inp = jax.tree_util.tree_map(lambda a: a[None], toks)
                out, new_view = step_fn(params, carry, inp,
                                        (pos0 + i)[None])
                return new_view, out

            view, outs = jax.lax.scan(body, view,
                                      (inputs, jnp.arange(K)))
            outs = jax.tree_util.tree_map(lambda a: a[:, 0], outs)
            def scat(p, v):
                vb = v[0].reshape((nb, bs) + p.shape[2:])
                return p.at[table].set(vb)
            new_pool = jax.tree_util.tree_map(scat, pool, view)
            return outs, new_pool

        jitted = jax.jit(_verify, donate_argnums=(1,)
                         if self._donate else ())
        pa, ka = self._param_avals(), self._pool_avals()
        ta = jax.ShapeDtypeStruct((nb,), jnp.int32)
        sa = jax.ShapeDtypeStruct((), jnp.int32)
        ia = {n: jax.ShapeDtypeStruct((K,) + sp.shape, sp.dtype)
              for n, sp in self._input_spec.items()}
        t0 = _time.perf_counter()
        lowered = jitted.lower(pa, ka, ta, sa, ia)
        self._verify_text = lowered.as_text()
        if _iraudit.enabled():
            self._audit("verify", "K%d" % K, self._verify_text)
        prog = lowered.compile()
        del lowered
        # caller (warm) holds self._lock for the whole build pass
        self._verify_prog = prog  # graftlint: disable=JG010
        self._count_compile("verify", K, _time.perf_counter() - t0)
        return prog

    def warm(self):
        """Build every tick/prefill (and verify) program and prime
        each with one throwaway-pool execution, so the first real
        session pays no one-time setup.  Returns programs built."""
        import jax
        import jax.numpy as jnp
        before = self._compiles
        with self._lock:
            for S in self.ladder.batches:
                if S not in self._tick_progs:
                    self._build_tick(S)
            if self._prefill_fn is not None:
                for Lr in self.prefill_rungs:
                    if Lr not in self._prefill_progs:
                        self._build_prefill(Lr)
            if self.spec_k > 0 and self._verify_prog is None:
                self._build_verify()
            # prime with zeros against a THROWAWAY pool — the real
            # pool's buffers must not ride a (possibly donating)
            # warmup call
            zero_pool = jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape, a.dtype),
                self._pool.arrays)
            nb = self.max_blocks
            for S, prog in self._tick_progs.items():
                outs, _ = prog(
                    self._params, zero_pool,
                    jnp.zeros((S, nb), jnp.int32),
                    jnp.zeros((S,), jnp.int32),
                    {n: jnp.zeros((S,) + sp.shape, sp.dtype)
                     for n, sp in self._input_spec.items()})
                for leaf in jax.tree_util.tree_leaves(outs):
                    leaf.block_until_ready()
                zero_pool = jax.tree_util.tree_map(
                    lambda a: jnp.zeros(a.shape, a.dtype),
                    self._pool.arrays)
            for Lr, prog in self._prefill_progs.items():
                new = prog(
                    self._params, zero_pool,
                    jnp.zeros((nb,), jnp.int32),
                    {n: jnp.zeros((1, Lr) + sp.shape, sp.dtype)
                     for n, sp in self._input_spec.items()},
                    jnp.int32(0))
                for leaf in jax.tree_util.tree_leaves(new):
                    leaf.block_until_ready()
                zero_pool = jax.tree_util.tree_map(
                    lambda a: jnp.zeros(a.shape, a.dtype),
                    self._pool.arrays)
        return self._compiles - before

    # -- session lifecycle ---------------------------------------------------
    def _normalize_prompt(self, prompt):
        if not isinstance(prompt, dict):
            if len(self._input_spec) != 1:
                raise ServeError(
                    "decode %r has %d inputs — pass a prompt dict"
                    % (self.label, len(self._input_spec)))
            prompt = {next(iter(self._input_spec)): prompt}
        out, length = {}, None
        for n, sp in self._input_spec.items():
            if n not in prompt:
                raise ServeError("decode %r: prompt is missing input "
                                 "%r" % (self.label, n))
            a = _np.asarray(prompt[n])
            if a.dtype != sp.dtype:
                a = a.astype(sp.dtype)
            if a.shape[1:] != sp.shape:
                raise ServeError(
                    "decode %r prompt input %r: per-token shape %s "
                    "does not match the spec %s"
                    % (self.label, n, a.shape[1:], sp.shape))
            if length is None:
                length = a.shape[0]
            elif a.shape[0] != length:
                raise ServeError("decode %r: prompt inputs disagree "
                                 "on length" % self.label)
            out[n] = a
        if not length:
            raise ServeError("decode %r: empty prompt" % self.label)
        if length > self.padded_len:
            raise ServeError(
                "decode %r: prompt length %d exceeds padded_len %d"
                % (self.label, length, self.padded_len))
        return out, length

    def admit(self, prompt, max_new_tokens=None, stop_fn=None,
              deadline_ms=None, journal_key=None, incarnation=0,
              resume_tokens=None):
        """Admission: validate the prompt, allocate its blocks (typed
        :class:`KVPoolExhausted` when the pool cannot hold it — shed
        at the front door), register the session and open its journal
        record.  Prefill/decode have not run yet — call
        :meth:`prefill` (the batcher does).

        *journal_key* is the ``(client, session_seq)`` identity (a
        direct session defaults to ``("local", sid)``); *incarnation*
        bumps on every resume.  *resume_tokens* (journaled host
        output trees) arms replay: after re-prefill the session
        replays them through ordinary ticks with delivery suppressed,
        each replayed output bit-checked — resume is bit-equal to an
        uninterrupted stream or fails typed."""
        prompt, length = self._normalize_prompt(prompt)
        with self._lock:
            if self._closed:
                raise ServeError("decode engine %r is closed"
                                 % self.label)
        n0 = _ceil_div(length, self.block_size)
        table = _np.zeros((self.max_blocks,), _np.int32)
        blocks = self._pool.alloc(n0, owner=self.label)
        table[:n0] = blocks
        deadline = (_time.monotonic() + float(deadline_ms) / 1e3
                    if deadline_ms else None)
        sess = PagedSession(self, prompt, length, blocks, table,
                            max_new_tokens, stop_fn, deadline)
        sess.journal_key = tuple(journal_key) if journal_key \
            else ("local", sess.sid)
        if resume_tokens:
            sess._replay = collections.deque(resume_tokens)
            sess._base = len(resume_tokens)
        rec = self._journal.open(
            sess.journal_key[0], sess.journal_key[1], incarnation,
            prompt, length, max_new_tokens=max_new_tokens,
            params_sha=self.params_sha())
        if resume_tokens and not rec.tokens:
            # a resume journaled elsewhere (router handoff): seed the
            # local log so replayed ticks dedup against it
            rec.tokens.extend(resume_tokens)
        with self._lock:
            if self._closed:
                self._pool.free(blocks)
                raise ServeError("decode engine %r is closed"
                                 % self.label)
            self._live.append(sess)
        _ACTIVE_SESSIONS.inc()
        _obs_events.emit("decode", kind="journal", sid=sess.sid,
                         model=self.label, client=str(rec.client),
                         session_seq=rec.seq,
                         incarnation=rec.incarnation,
                         params_sha=rec.params_sha,
                         tokens_logged=len(rec.tokens))
        _obs_events.emit("decode", kind="session_start", sid=sess.sid,
                         model=self.label, prompt_len=length,
                         blocks=n0,
                         max_new_tokens=max_new_tokens,
                         resume=bool(resume_tokens))
        return sess

    def prefill(self, sess):
        """Run the session's bucketed prefill dispatch (the prompt
        prefix, everything but its last token) and arm the first
        decode tick.  One dispatch regardless of prompt length."""
        import jax
        import jax.numpy as jnp
        with self._lock:
            if sess.done():
                return
            prefix = sess.length - 1
            if prefix > 0:
                if self._prefill_fn is None:
                    raise ServeError(
                        "decode %r has no prefill_fn but got a "
                        "prompt of length %d — prompts must be "
                        "single-token" % (self.label, sess.length))
                rung = None
                for r in self.prefill_rungs:
                    if r >= prefix:
                        rung = r
                        break
                prog = self._prefill_progs[rung]
                inputs = {}
                for n, sp in self._input_spec.items():
                    buf = _np.zeros((1, rung) + sp.shape, sp.dtype)
                    buf[0, :prefix] = sess.prompt[n][:prefix]
                    inputs[n] = buf
                old = jax.tree_util.tree_leaves(self._pool.arrays) \
                    if self._donate and _san.enabled("donation") \
                    else None
                t0 = _time.perf_counter()
                with _san.transfer_guard("decode prefill (%s)"
                                         % self.label):
                    new_pool = prog(self._params, self._pool.arrays,
                                    jnp.asarray(sess.table),
                                    inputs, _np.int32(prefix))
                _DISPATCH_SECONDS.observe(_time.perf_counter() - t0)
                self._pool.set_arrays(new_pool)
                self._dispatches += 1
                if old is not None:
                    _san.poison_donated(
                        old, "decode prefill (%s)" % self.label)
            sess.pos = prefix
            sess.pending_input = {
                n: sess.prompt[n][sess.length - 1]
                for n in self._input_spec}

    def tick(self, sessions):
        """ONE batched decode step for *sessions*: gather, step,
        scatter, readback — every live session's next token from one
        dispatch.  Cancelled sessions are released; a session that
        needs a block the pool cannot give fails typed and releases
        its blocks; finished sessions (max tokens, stop_fn, length
        cap) are released with their reason.  Returns the sessions
        that actually rode the dispatch."""
        import jax
        _servechaos.on_decode_tick(self.label)
        with self._lock:
            if self._closed:
                raise ServeError("decode engine %r is closed"
                                 % self.label)
            ready = []
            for s in sessions:
                if s.done():
                    continue
                if s.cancelled:
                    self._release_locked(
                        s, "cancelled", RequestCancelled(
                            "decode session %d cancelled by its "
                            "caller" % s.sid))
                    continue
                if s.pos >= self.padded_len:
                    self._release_locked(s, "length_cap", None)
                    continue
                need = s.pos // self.block_size + 1
                failed = False
                while len(s.blocks) < need:
                    try:
                        blk = self._pool.alloc(1, owner=self.label)
                    except KVPoolExhausted as exc:
                        self._release_locked(s, "pool_exhausted", exc)
                        failed = True
                        break
                    s.blocks.extend(blk)
                    s.table[len(s.blocks) - 1] = blk[0]
                if not failed:
                    ready.append(s)
            if not ready:
                return []
            n = len(ready)
            S = self.ladder.batch_for(n)
            nb = self.max_blocks
            tables = _np.zeros((S, nb), _np.int32)
            pos = _np.zeros((S,), _np.int32)
            inputs = {nm: _np.zeros((S,) + sp.shape, sp.dtype)
                      for nm, sp in self._input_spec.items()}
            for i, s in enumerate(ready):
                tables[i] = s.table
                pos[i] = s.pos
                for nm in inputs:
                    inputs[nm][i] = s.pending_input[nm]
            prog = self._tick_progs[S]
            old = jax.tree_util.tree_leaves(self._pool.arrays) \
                if self._donate and _san.enabled("donation") else None
            t0 = _time.perf_counter()
            with _san.transfer_guard("decode tick (%s)" % self.label):
                outs, new_pool = prog(self._params, self._pool.arrays,
                                      tables, pos, inputs)
            _DISPATCH_SECONDS.observe(_time.perf_counter() - t0)
            self._pool.set_arrays(new_pool)
            self._dispatches += 1
            _DECODE_STEPS.inc()
            if old is not None:
                _san.poison_donated(old, "decode tick (%s)"
                                    % self.label)
            # ONE device->host readback serves every session's token
            host = jax.device_get(outs)
            now = _time.monotonic()
            for i, s in enumerate(ready):
                out_i = jax.tree_util.tree_map(lambda a: a[i], host)
                s.pos += 1
                if s._replay:
                    # replayed tick of a resumed session: the token
                    # was accepted (and delivered) before the crash —
                    # bit-check it against the journal, advance the
                    # cache, suppress delivery/counters.  Finish
                    # checks are skipped: the session was live when
                    # it journaled this token, and greedy replay is
                    # deterministic.
                    expect = s._replay.popleft()
                    if _token_bytes(out_i) != _token_bytes(expect):
                        self._release_locked(
                            s, "resume_divergence", ServeError(
                                "decode session %d resume diverged "
                                "at token %d — replayed output is "
                                "not bit-equal to the journal "
                                "(params or program drift)"
                                % (s.sid, s.token_count)))
                        continue
                    s.pending_input = self._feed(out_i)
                    continue
                s._deliver(out_i, now)
                _DECODE_TOKENS.inc()
                self._journal.append(s.journal_key,
                                     s._base + s.token_count - 1,
                                     out_i)
                if self._finished(s, out_i):
                    self._release_locked(s, "finished", None)
                else:
                    s.pending_input = self._feed(out_i)
            _obs_events.emit("decode", kind="tick", model=self.label,
                             rung=S, sessions=n)
            return ready

    def _finished(self, s, out):
        if s.max_new_tokens is not None and \
                s._base + s.token_count >= s.max_new_tokens:
            return True
        if s.stop_fn is not None and s.stop_fn(out):
            return True
        return False

    def _feed(self, out):
        if self._next_input_fn is not None:
            return self._next_input_fn(out)
        import jax
        if isinstance(out, dict) and set(out) == set(self._input_spec):
            return {n: _np.asarray(out[n]).astype(
                self._input_spec[n].dtype) for n in out}
        leaves = jax.tree_util.tree_leaves(out)
        if len(leaves) == 1 and len(self._input_spec) == 1:
            name, sp = next(iter(self._input_spec.items()))
            a = _np.asarray(leaves[0]).astype(sp.dtype)
            if a.shape != sp.shape:
                raise ServeError(
                    "decode %r: step output shape %s does not match "
                    "input spec %s — pass next_input_fn"
                    % (self.label, a.shape, sp.shape))
            return {name: a}
        raise ServeError(
            "decode %r: cannot map the step output back to the "
            "inputs — pass next_input_fn" % self.label)

    # -- speculative verify (stretch) ----------------------------------------
    def verify(self, sess, tokens):
        """One K-token verify dispatch (``spec_k`` contract): run the
        step at positions ``pos .. pos+K-1`` with *tokens* (host
        arrays, leaves ``(K,) + input_shape``) and return the K step
        outputs, WITHOUT advancing the session — the caller commits
        the accepted prefix via :meth:`spec_commit`.  Rejected
        positions hold beyond-position garbage the next real tick
        overwrites."""
        import jax
        if self._verify_prog is None:
            raise ServeError("decode %r was built without spec_k — "
                             "speculative verify is off" % self.label)
        K = self.spec_k
        with self._lock:
            if sess.done():
                raise ServeError("decode session %d is finished"
                                 % sess.sid)
            if sess.pos + K > self.padded_len:
                raise ServeError(
                    "verify of %d tokens at pos %d crosses padded_len "
                    "%d" % (K, sess.pos, self.padded_len))
            need = (sess.pos + K - 1) // self.block_size + 1
            while len(sess.blocks) < need:
                try:
                    blk = self._pool.alloc(1, owner=self.label)
                except KVPoolExhausted:
                    # same typed-fail-and-release rule as tick(): the
                    # session must not keep its blocks (or the
                    # active-sessions gauge) after a growth failure
                    self._release_locked(
                        sess, "pool_exhausted", KVPoolExhausted(
                            "decode session %d exhausted the pool "
                            "growing for a %d-token verify"
                            % (sess.sid, K)))
                    raise
                sess.blocks.extend(blk)
                sess.table[len(sess.blocks) - 1] = blk[0]
            inputs = {}
            for n, sp in self._input_spec.items():
                a = _np.asarray(tokens[n]).astype(sp.dtype)
                if a.shape != (K,) + sp.shape:
                    raise ServeError(
                        "verify input %r: shape %s != %s"
                        % (n, a.shape, (K,) + sp.shape))
                inputs[n] = a
            old = jax.tree_util.tree_leaves(self._pool.arrays) \
                if self._donate and _san.enabled("donation") else None
            t0 = _time.perf_counter()
            with _san.transfer_guard("decode verify (%s)" % self.label):
                outs, new_pool = self._verify_prog(
                    self._params, self._pool.arrays, sess.table,
                    _np.int32(sess.pos), inputs)
            _DISPATCH_SECONDS.observe(_time.perf_counter() - t0)
            self._pool.set_arrays(new_pool)
            self._dispatches += 1
            if old is not None:
                _san.poison_donated(old, "decode verify (%s)"
                                    % self.label)
            return jax.device_get(outs)

    def spec_commit(self, sess, accepted_outs):
        """Commit *accepted_outs* (host per-token output trees, in
        order) after a :meth:`verify`: deliver each, advance the
        cursor, arm the next input from the last one."""
        with self._lock:
            now = _time.monotonic()
            for out in accepted_outs:
                if sess.done():
                    return
                sess.pos += 1
                sess._deliver(out, now)
                _DECODE_TOKENS.inc()
                self._journal.append(sess.journal_key,
                                     sess._base + sess.token_count - 1,
                                     out)
                if self._finished(sess, out):
                    self._release_locked(sess, "finished", None)
                else:
                    sess.pending_input = self._feed(out)

    # -- fault tolerance -----------------------------------------------------
    def rebuild_pool(self):
        """Quarantine the current pool and swap in a fresh, empty
        same-shape one — the crashed-tick recovery primitive.  A
        dispatch that died mid-donation leaves the pool state
        untrustworthy; a clone has identical leaf avals, so every
        already-warm tick/prefill/verify program runs it with ZERO
        new compiles (asserted).  Live sessions' block tables are
        cleared FIRST (their ids belong to the quarantined pool and
        must never be freed into the fresh one) — the caller must
        then :meth:`readmit` or :meth:`release` every live session."""
        with self._lock:
            if self._closed:
                raise ServeError("decode engine %r is closed"
                                 % self.label)
            before = self._compiles
            old = self._pool
            for s in self._live:
                with s._cond:
                    s.blocks = []
                s.table = _np.zeros((self.max_blocks,), _np.int32)
                s.pos = 0
                s.pending_input = None
            self._pool = old.clone_empty()
            old.close()
            self._rebuilds += 1
            if self._compiles != before:
                raise ServeError(
                    "decode %r: pool rebuild compiled %d new "
                    "program(s) — the fresh pool's avals drifted "
                    "from the quarantined one's" % (
                        self.label, self._compiles - before))
        return self._pool

    def readmit(self, sess):
        """Re-admit a live journaled session onto the current (fresh)
        pool after :meth:`rebuild_pool`: fresh prompt blocks (typed
        :class:`KVPoolExhausted` sheds it without wedging the
        rebuild), cursor reset, replay armed from the journal.  The
        batcher then re-prefills it and replays its accepted tokens
        through ordinary ticks — delivery suppressed and bit-checked,
        so the caller-visible stream continues exactly where it
        stopped."""
        with self._lock:
            if self._closed:
                raise ServeError("decode engine %r is closed"
                                 % self.label)
            if sess.done():
                return sess
            tokens = self._journal.tokens(sess.journal_key) \
                if sess.journal_key is not None else list(sess.outputs())
            n0 = _ceil_div(sess.length, self.block_size)
            blocks = self._pool.alloc(n0, owner=self.label)
            table = _np.zeros((self.max_blocks,), _np.int32)
            table[:n0] = blocks
            with sess._cond:
                sess.blocks = list(blocks)
            sess.table = table
            sess.pos = 0
            sess.pending_input = None
            sess._replay = collections.deque(tokens)
            # the join deadline bounded time-to-FIRST-join; a
            # re-admission must not expire a session that already
            # joined before the crash
            sess._deadline = None
            if sess not in self._live:
                self._live.append(sess)
                _ACTIVE_SESSIONS.inc()
        _RESUMED_TOTAL.inc()
        _obs_events.emit("decode", kind="resume", sid=sess.sid,
                         model=self.label,
                         tokens_replayed=len(tokens))
        return sess

    # -- teardown ------------------------------------------------------------
    def release(self, sess, reason, error=None):
        """Finish a session: free its blocks, resolve its readers
        (typed *error*, or a clean finish), drop it from the live
        set.  Serialized with tick/prefill dispatches — blocks are
        never freed under a program that still reads them."""
        with self._lock:
            self._release_locked(sess, reason, error)

    def _release_locked(self, sess, reason, error):
        with sess._cond:
            if sess._released:
                return
            sess._released = True
            blocks, sess.blocks = sess.blocks, []
        self._pool.free(blocks)
        try:
            self._live.remove(sess)
        except ValueError:
            pass
        _ACTIVE_SESSIONS.dec()
        with sess._cond:
            sess._done = True
            sess._error = error
            sess.finish_reason = reason
            sess._cond.notify_all()
        if sess.journal_key is not None:
            self._journal.close(sess.journal_key, reason)
        _obs_events.emit("decode", kind="session_end", sid=sess.sid,
                         model=self.label, reason=reason,
                         tokens=sess.token_count,
                         error=None if error is None
                         else type(error).__name__)

    def close(self):
        """Tear the engine down: fail live sessions typed, release
        the pool (gauges drop), drop the programs.  Close batchers
        first (the registry does)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for s in list(self._live):
                self._release_locked(
                    s, "closed", ServeError(
                        "decode engine %r closed" % self.label))
            self._tick_progs = {}
            self._prefill_progs = {}
            self._verify_prog = None
            # inside the engine lock: every other access to the
            # pool's state handle (tick/prefill gather + rebind)
            # holds it too — close must share that lockset
            self._pool.close()


class DecodeBatcher:
    """The continuous-batching decode tick loop.

    One dispatcher thread owns the engine: it admits queued joins
    (bucketed prefill dispatches), then runs decode ticks over the
    whole active-session set — one dispatch + one readback per tick
    serves every session's next token.  Sessions join and leave
    between ticks; an idle batcher coalesces arrivals for up to
    ``MXNET_SERVE_DECODE_MAX_WAIT_MS`` before the first tick, exactly
    like the predict batcher's window.

    Supervision: a crash escaping the tick loop cannot simply restart
    over the same pool — the donated state cannot be trusted after a
    dispatch died mid-donation, and decoding over a corrupt pool
    would serve wrong tokens instead of a typed error.  Instead the
    batcher QUARANTINES the suspect pool (``engine.rebuild_pool``
    swaps in a fresh same-shape one against the already-warm
    programs, zero new compiles), re-admits every journaled live
    session via re-prefill + replayed ticks (bit-checked, so the
    caller-visible stream continues seamlessly; a session the fresh
    pool cannot hold sheds typed without wedging the rebuild) and
    restarts the tick loop on a fresh thread — bounded by
    ``MXNET_SERVE_DECODE_REBUILDS``.  Past the budget it degrades to
    the old behavior: unhealthy forever, every session failed
    typed."""

    def __init__(self, engine, max_wait_ms=None, name=None,
                 on_state=None, rebuilds=None):
        from ..config import resolve_env
        self._engine = engine
        self.name = name or engine.label
        if max_wait_ms is None:
            tcfg = (getattr(engine, "tuning", None) or {}) \
                .get("config") or {}
            max_wait_ms = resolve_env(
                "MXNET_SERVE_DECODE_MAX_WAIT_MS",
                tcfg.get("MXNET_SERVE_DECODE_MAX_WAIT_MS"))
        self._max_wait = max(0.0, float(max_wait_ms)) / 1e3
        self._on_state = on_state
        if rebuilds is None:
            rebuilds = resolve_env("MXNET_SERVE_DECODE_REBUILDS", None)
        self._rebuild_budget = max(0, int(rebuilds))
        self._rebuilds = 0
        self._rebuilding = False
        self._lock = _san.lock(label="serve.decode.batcher.%s"
                               % self.name)
        self._cond = _san.condition(self._lock,
                                    label="serve.decode.batcher.%s"
                                    % self.name)
        self._joins = collections.deque()
        self._sessions = []
        # sessions/joins the tick loop has popped into its locals but
        # not yet written back — drain()/close()/_crashed() must see
        # them or a mid-iteration drain returns early and teardown
        # closes the engine under a live session (the DynamicBatcher
        # _inflight discipline)
        self._inflight = ()
        self._stopped = False
        self._draining = False
        self._unhealthy = False
        self._ticks = 0
        self._last_tick = _time.monotonic()
        _san.track(self, ("_joins", "_sessions", "_inflight",
                          "_stopped", "_draining", "_unhealthy",
                          "_rebuilding", "_rebuilds", "_ticks"),
                   label="serve.decode.batcher.%s" % self.name)
        with engine._lock:
            engine._batchers.append(self)
        self._thread = _san.thread(
            target=self._run, name="serve-decode-%s" % self.name,
            daemon=True)
        self._thread.start()

    # -- stats / health ------------------------------------------------------
    @property
    def engine(self):
        return self._engine

    @property
    def tick_count(self):
        with self._lock:
            return self._ticks

    @property
    def session_count(self):
        with self._lock:
            return len(self._sessions) + len(self._joins)

    @property
    def unhealthy(self):
        with self._lock:
            return self._unhealthy

    @property
    def rebuilding(self):
        with self._lock:
            return self._rebuilding

    @property
    def rebuild_count(self):
        with self._lock:
            return self._rebuilds

    @property
    def rebuild_budget(self):
        return self._rebuild_budget

    @property
    def draining(self):
        with self._lock:
            return self._draining

    @property
    def stopped(self):
        """True after close(): a retired batcher, not a failed one."""
        with self._lock:
            return self._stopped

    def dispatcher_alive(self):
        with self._lock:
            thread, unhealthy = self._thread, self._unhealthy
        return bool(thread.is_alive()) and not unhealthy

    def last_tick_age(self):
        with self._lock:
            return _time.monotonic() - self._last_tick

    def health_state(self):
        with self._lock:
            if self._unhealthy:
                return "unhealthy"
            if self._rebuilding:
                return "rebuilding"
            if self._stopped or self._draining:
                return "draining"
            return "ready"

    def rebuild_state(self):
        """The quarantine/rebuild surface for ``health(name)``:
        spent/budgeted rebuild counts and whether a rebuild is in
        flight right now."""
        with self._lock:
            return {"rebuilds": self._rebuilds,
                    "budget": self._rebuild_budget,
                    "rebuilding": self._rebuilding}

    # -- client side ---------------------------------------------------------
    def start(self, prompt, max_new_tokens=None, stop_fn=None,
              deadline_ms=None, journal_key=None, incarnation=0,
              resume_tokens=None):
        """Admit one decode session.  Raises a typed
        :class:`KVPoolExhausted` when the pool cannot hold the prompt
        (shed at submit — PR-10 semantics), a :class:`ServeError`
        when the batcher is draining/closed/unhealthy.
        *deadline_ms* bounds time-to-join: a session the dispatcher
        cannot prefill by then is shed typed
        (:class:`~mxnet_tpu.serve.buckets.DeadlineExceededError`).
        *journal_key*/*incarnation*/*resume_tokens* pass through to
        :meth:`DecodeEngine.admit` — the wire-resume path (a router
        re-opening a journaled session here after its old replica
        died).  Returns the :class:`PagedSession`."""
        with self._lock:
            if self._stopped:
                raise ServeError("decode batcher %r is closed"
                                 % self.name)
            if self._unhealthy:
                raise ServeError("decode batcher %r is unhealthy "
                                 "(tick loop crashed)" % self.name)
            if self._rebuilding:
                raise ServeError("decode batcher %r is rebuilding "
                                 "its pool after a tick-loop crash — "
                                 "admissions shed until the rebuild "
                                 "lands" % self.name)
            if self._draining:
                raise ServeError("decode batcher %r is draining — "
                                 "admissions are stopped" % self.name)
        sess = self._engine.admit(prompt,
                                  max_new_tokens=max_new_tokens,
                                  stop_fn=stop_fn,
                                  deadline_ms=deadline_ms,
                                  journal_key=journal_key,
                                  incarnation=incarnation,
                                  resume_tokens=resume_tokens)
        with self._cond:
            if self._stopped or self._draining:
                stopped = self._stopped
                self._cond.notify_all()
            else:
                self._joins.append(sess)
                self._cond.notify()
                return sess
        # lost the race to a close/drain: undo the admission, typed
        self._engine.release(sess, "shed", ServeError(
            "decode batcher %r %s" % (self.name,
                                      "closed" if stopped
                                      else "draining")))
        raise sess.error

    # -- dispatcher ----------------------------------------------------------
    def _run(self):
        try:
            self._loop()
        except Exception as exc:
            self._crashed(exc)

    def _loop(self):
        eng = self._engine
        top = eng.ladder.max_batch
        while True:
            with self._cond:
                self._last_tick = _time.monotonic()
                while not self._stopped and not self._joins and \
                        not self._sessions:
                    # bounded idle wait keeps the liveness tick fresh
                    self._cond.wait(timeout=0.5)
                    self._last_tick = _time.monotonic()
                if self._stopped:
                    return
                # coalescing window: with nothing decoding yet, hold
                # the first tick open for more arrivals (oldest-join
                # clock, monotonic) so co-arriving sessions share one
                # rung from the start
                while self._joins and not self._sessions and \
                        not self._stopped and not self._draining and \
                        len(self._joins) < top:
                    now = _time.monotonic()
                    window = self._joins[0]._t_enq + self._max_wait
                    if now >= window:
                        break
                    self._cond.wait(timeout=window - now)
                    self._last_tick = _time.monotonic()
                if self._stopped:
                    return
                joins = list(self._joins)
                self._joins.clear()
                sessions = list(self._sessions)
                self._inflight = tuple(joins) + tuple(sessions)
            for j in joins:
                if j.cancelled:
                    eng.release(j, "cancelled", RequestCancelled(
                        "decode session %d cancelled before its "
                        "prefill" % j.sid))
                    continue
                # fresh clock per join: an earlier join's slow
                # prefill must not let a stale stamp admit a session
                # whose deadline has already passed
                if j._deadline is not None and \
                        _time.monotonic() >= j._deadline:
                    eng.release(j, "expired", DeadlineExceededError(
                        "decode session %d missed its join deadline "
                        "(%r queue)" % (j.sid, self.name)))
                    continue
                try:
                    eng.prefill(j)
                except Exception as exc:
                    # a failed prefill fails exactly this session —
                    # the error rides its future, typed
                    eng.release(j, "prefill_failed", exc)
                    continue
                sessions.append(j)
            live = [s for s in sessions if not s.done()]
            for i in range(0, len(live), top):
                eng.tick(live[i:i + top])
            with self._cond:
                self._inflight = ()
                self._sessions = [s for s in sessions
                                  if not s.done()]
                self._ticks += 1
                self._last_tick = _time.monotonic()
                # wake waiters every iteration: a flush() watching a
                # SUBSET of sessions must see them finish even while
                # new admissions keep the lists non-empty
                self._cond.notify_all()

    def _crashed(self, exc):
        with self._cond:
            leftovers = list(dict.fromkeys(
                self._sessions + list(self._joins)
                + list(self._inflight)))
            self._sessions = []
            self._joins.clear()
            self._inflight = ()
            rebuild = (not self._stopped
                       and self._rebuilds < self._rebuild_budget)
            if rebuild:
                self._rebuilding = True
                self._rebuilds += 1
                nth = self._rebuilds
            else:
                self._unhealthy = True
            self._cond.notify_all()
        if rebuild:
            self._rebuild(exc, leftovers, nth)
        else:
            self._fail_unhealthy(exc, leftovers)

    def _fail_unhealthy(self, exc, leftovers):
        """Past the rebuild budget (or closed): the pre-rebuild
        behavior, verbatim — unhealthy forever, every session failed
        typed, delivered tokens stay readable."""
        log.error("decode batcher %r: tick loop crashed (%s: %s) — "
                  "unhealthy, failing %d sessions (no restart: the "
                  "donated pool state cannot be trusted)", self.name,
                  type(exc).__name__, exc, len(leftovers))
        err = ServeError(
            "decode batcher %r is unhealthy: tick loop crashed "
            "(%s: %s)" % (self.name, type(exc).__name__, exc))
        for s in leftovers:
            self._engine.release(s, "failed", err)
        _obs_events.emit("decode", kind="unhealthy", model=self.name,
                         sessions_failed=len(leftovers),
                         error="%s: %s" % (type(exc).__name__,
                                           str(exc)[:200]))
        if self._on_state is not None:
            try:
                self._on_state("unhealthy")
            except Exception:
                log.exception("decode batcher %r: on_state hook "
                              "failed", self.name)

    def _rebuild(self, exc, leftovers, nth):
        """Quarantine-and-rebuild (runs ON the dying dispatcher
        thread): swap in a fresh pool against the warm programs,
        re-admit journaled live sessions via re-prefill + replay,
        hand the loop to a fresh thread."""
        eng = self._engine
        log.warning("decode batcher %r: tick loop crashed (%s: %s) — "
                    "quarantining the pool and rebuilding (%d/%d), "
                    "%d sessions to re-admit", self.name,
                    type(exc).__name__, exc, nth,
                    self._rebuild_budget, len(leftovers))
        compiles_before = eng.compile_count
        try:
            eng.rebuild_pool()
        except Exception as rexc:
            # the rebuild itself failed: degrade to the typed-fail
            # terminal state — never hang, never retry-loop here
            log.exception("decode batcher %r: pool rebuild failed",
                          self.name)
            with self._cond:
                self._rebuilding = False
                self._unhealthy = True
                self._cond.notify_all()
            self._fail_unhealthy(rexc, leftovers)
            return
        _REBUILDS_TOTAL.inc()
        _obs_events.emit("decode", kind="rebuild", model=self.name,
                         rebuilds=nth,
                         budget=self._rebuild_budget,
                         sessions=len(leftovers),
                         compiles_before=compiles_before,
                         compiles_after=eng.compile_count,
                         error="%s: %s" % (type(exc).__name__,
                                           str(exc)[:200]))
        if self._on_state is not None:
            # after the fresh pool, before re-admission: lets a
            # registry hook (or a test seam) observe "rebuilding"
            # while re-admission can still shed typed
            try:
                self._on_state("rebuilding")
            except Exception:
                log.exception("decode batcher %r: on_state hook "
                              "failed", self.name)
        readmitted = []
        for s in leftovers:
            if s.done():
                continue
            if s.cancelled:
                # a cancel racing the crash wins: never resumed
                eng.release(s, "cancelled", RequestCancelled(
                    "decode session %d cancelled during the pool "
                    "rebuild" % s.sid))
                continue
            try:
                eng.readmit(s)
            except KVPoolExhausted as aexc:
                # shed THIS session typed; the rebuild itself lands
                eng.release(s, "pool_exhausted", aexc)
                continue
            except Exception as aexc:
                eng.release(s, "failed", aexc)
                continue
            readmitted.append(s)
        with self._cond:
            self._joins.extend(readmitted)
            self._rebuilding = False
            # the crash handler runs on the dying thread — a fresh
            # one must own the loop from here
            self._thread = _san.thread(
                target=self._run,
                name="serve-decode-%s" % self.name, daemon=True)
            self._thread.start()
            self._cond.notify_all()
        log.info("decode batcher %r: rebuild %d/%d complete — "
                 "%d/%d sessions re-admitted", self.name,
                 nth, self._rebuild_budget,
                 len(readmitted), len(leftovers))

    # -- lifecycle -----------------------------------------------------------
    def drain(self, timeout=None):
        """Stop admissions (``start`` raises typed) and keep ticking
        until every live session finishes, bounded by *timeout*
        (default ``MXNET_SERVE_DRAIN_TIMEOUT``).  Sessions still live
        at the deadline fail typed and release their pool blocks —
        a cutover/unload never strands blocks, and tokens already
        delivered stay readable (zero lost accepted steps).  Returns
        True when everything finished naturally."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        _obs_events.emit("decode", kind="drain", model=self.name)
        return self._await_quiesce(timeout, "drained")

    def flush(self, timeout=None):
        """Wait (bounded) for every session ALREADY accepted to
        finish WITHOUT stopping admissions — the alias-cutover
        primitive, mirroring DynamicBatcher.flush: accepted decode
        work lands (or typed-fails at the deadline, releasing its
        blocks), and the batcher keeps serving — the model may still
        be reachable through other aliases or its direct name.
        Returns True when everything finished in time."""
        return self._await_quiesce(timeout, "flushed")

    def _await_quiesce(self, timeout, reason):
        if timeout is None:
            from ..config import get_env
            timeout = get_env("MXNET_SERVE_DRAIN_TIMEOUT")
        deadline = _time.monotonic() + max(0.0, float(timeout))
        clean = True
        leftovers = []
        with self._cond:
            # snapshot what is accepted NOW — flush must not chase
            # sessions admitted after it started
            target = set(self._sessions) | set(self._joins) \
                | set(self._inflight)
            while any(not s.done() for s in target):
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    clean = False
                    leftovers = [s for s in target if not s.done()]
                    self._sessions = [s for s in self._sessions
                                      if s not in leftovers]
                    for s in leftovers:
                        try:
                            self._joins.remove(s)
                        except ValueError:
                            pass
                    break
                self._cond.wait(timeout=remaining)
        for s in leftovers:
            self._engine.release(s, reason, ServeError(
                "decode session %d %s before finishing "
                "(batcher %r); tokens delivered so far remain "
                "readable via outputs()" % (s.sid, reason,
                                            self.name)))
        return clean

    def close(self, timeout=5.0):
        """Stop the tick loop; live sessions fail typed (their
        delivered tokens stay readable).  Returns True on a clean
        join."""
        with self._cond:
            if self._stopped:
                return True
            self._stopped = True
            self._cond.notify_all()
            thread = self._thread
        # join FIRST: the loop finishes its in-flight iteration and
        # writes surviving sessions back, so the sweep below sees
        # them (failing leftovers before the join would miss the
        # iteration's local state)
        thread.join(timeout)
        clean = not thread.is_alive()
        with self._cond:
            leftovers = list(dict.fromkeys(
                self._sessions + list(self._joins)
                + list(self._inflight)))
            self._sessions = []
            self._joins.clear()
            self._inflight = ()
        for s in leftovers:
            self._engine.release(s, "closed", ServeError(
                "decode batcher %r closed before session %d "
                "finished" % (self.name, s.sid)))
        # a cleanly-retired batcher must not haunt the registry's
        # live()/health view (its dead thread is not a liveness
        # failure); a CRASHED batcher stays listed — unhealthy must
        # surface
        with self._engine._lock:
            try:
                self._engine._batchers.remove(self)
            except ValueError:
                pass
        if not clean:
            log.warning("decode batcher %r: close could not join the "
                        "tick loop within %.1fs", self.name, timeout)
        return clean


class SpeculativeDecoder:
    """Greedy speculative decode (stretch feature, opt-in): a small
    draft engine proposes K tokens with K cheap rung-1 ticks, the
    target engine verifies all K in ONE batched verify dispatch and
    accepts the matched prefix plus one corrected token.  With greedy
    (argmax) emission this is bit-equal to plain target decode: every
    emitted token is the target's own step output, and rejected cache
    positions are beyond-position garbage the step contract already
    masks.

    Build the target engine with ``spec_k=K`` (that compiles the
    verify program at warm); the draft engine is any
    :class:`DecodeEngine` over the same input/output token contract
    (typically a much smaller model).  This is a single-session
    driver — the batched tick path stays the default; speculative
    decode is the latency play for sparse traffic.

    Degradation: a draft-engine failure (crash, pool exhaustion,
    rebuild in progress) falls back to plain greedy target ticks for
    the rest of the run — invisible to callers, since bit-equality
    to greedy already holds; ``fallback_reason`` and a ``decode``
    event of kind ``spec_fallback`` name the cause.
    """

    def __init__(self, target, draft):
        if target.spec_k < 1:
            raise ServeError("SpeculativeDecoder needs a target "
                             "engine built with spec_k >= 1")
        if set(draft._input_spec) != set(target._input_spec):
            raise ServeError("draft/target engines disagree on the "
                             "input contract")
        self.target = target
        self.draft = draft
        self.k = target.spec_k
        self.stats = {"rounds": 0, "proposed": 0, "accepted": 0,
                      "target_dispatches": 0, "fallbacks": 0}
        # a draft-engine failure (crash, pool exhaustion, rebuild in
        # progress) degrades this run to plain greedy target ticks —
        # bit-equal to greedy already holds, so callers never see it
        self.fallback_reason = None

    def _token_key(self, out):
        return _token_bytes(out)

    def _fall_back(self, reason, exc, d_sess=None):
        """Degrade to plain greedy ticks: note why, emit the decode
        event, retire the draft session.  The stream is unaffected —
        every emitted token is the target's own step output either
        way."""
        self.fallback_reason = reason
        self.stats["fallbacks"] += 1
        log.warning("speculative decode %r: draft engine failed "
                    "(%s: %s) — falling back to plain greedy ticks",
                    self.target.label, reason, exc)
        _obs_events.emit("decode", kind="spec_fallback",
                         model=self.target.label, reason=reason,
                         error=None if exc is None else
                         "%s: %s" % (type(exc).__name__,
                                     str(exc)[:200]))
        if d_sess is not None and not d_sess.done():
            try:
                self.draft.release(d_sess, "failed", ServeError(
                    "draft engine abandoned: %s" % reason))
            except Exception:
                log.exception("speculative decode %r: draft release "
                              "failed", self.target.label)

    def run(self, prompt, max_new_tokens):
        """Decode one session speculatively; returns the finished
        target :class:`PagedSession` (its ``outputs()`` is the
        stream)."""
        t_sess = self.target.admit(prompt,
                                   max_new_tokens=max_new_tokens)
        self.target.prefill(t_sess)
        d_sess = None
        try:
            d_sess = self.draft.admit(prompt)
            self.draft.prefill(d_sess)
        except Exception as exc:
            self._fall_back("draft_admit", exc, d_sess)
            d_sess = None
        try:
            while not t_sess.done():
                if self.fallback_reason is None and d_sess is not None \
                        and d_sess.done() and d_sess.error is not None:
                    # the draft died typed mid-run (pool exhausted,
                    # engine closed/rebuilding): permanent fallback
                    self._fall_back(
                        "draft_%s" % (d_sess.finish_reason
                                      or "failed"), d_sess.error)
                if self.fallback_reason is not None:
                    self.target.tick([t_sess])
                    self.stats["target_dispatches"] += 1
                    continue
                base_pos = t_sess.pos
                base_input = dict(t_sess.pending_input)
                # draft proposes continuations of the pending token.
                # k draft ticks: the first k-1 proposals ride the
                # verify (inputs = pending + proposals[:k-1]); the
                # k-th tick exists only to write draft-cache position
                # base+k-1, so a FULL accept leaves the draft's cache
                # complete for the next round (without it the next
                # proposals would read beyond-position garbage and
                # acceptance collapses after every clean round)
                d_sess.pos = base_pos
                d_sess.pending_input = dict(base_input)
                proposals = []
                try:
                    for _ in range(self.k):
                        if d_sess.pos >= self.draft.padded_len:
                            break
                        before = d_sess.token_count
                        self.draft.tick([d_sess])
                        if d_sess.token_count == before:
                            break
                        proposals.append(d_sess.outputs()[-1])
                except Exception as exc:
                    # a draft crash degrades, never surfaces: the
                    # target continues on plain greedy ticks
                    self._fall_back("draft_tick", exc, d_sess)
                    continue
                if len(proposals) < self.k:
                    # tail of the sequence: fall back to plain ticks
                    self.target.tick([t_sess])
                    self.stats["target_dispatches"] += 1
                    continue
                proposals = proposals[:self.k - 1]
                verify_inputs = {}
                for n, sp in self.target._input_spec.items():
                    buf = _np.zeros((self.k,) + sp.shape, sp.dtype)
                    buf[0] = base_input[n]
                    for i, p in enumerate(proposals):
                        buf[i + 1] = self.target._feed(p)[n]
                    verify_inputs[n] = buf
                outs = self.target.verify(t_sess, verify_inputs)
                self.stats["target_dispatches"] += 1
                self.stats["rounds"] += 1
                self.stats["proposed"] += len(proposals)
                import jax
                per_tok = [jax.tree_util.tree_map(lambda a: a[i], outs)
                           for i in range(self.k)]
                accepted = [per_tok[0]]
                for i, p in enumerate(proposals):
                    if self._token_key(p) == \
                            self._token_key(per_tok[i]):
                        accepted.append(per_tok[i + 1])
                    else:
                        break
                self.stats["accepted"] += len(accepted) - 1
                self.target.spec_commit(t_sess, accepted)
        except BaseException as exc:
            # a verify/tick failure must not strand the live target
            # session: its blocks and the active-sessions gauge have
            # to come back (delivered tokens stay readable)
            if not t_sess.done():
                self.target.release(t_sess, "failed", ServeError(
                    "speculative decode failed mid-stream "
                    "(%s: %s)" % (type(exc).__name__, exc)))
            raise
        finally:
            if d_sess is not None and not d_sess.done():
                self.draft.release(d_sess, "finished", None)
        return t_sess
