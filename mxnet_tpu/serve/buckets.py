"""Padding-bucket ladder — the static-shape contract of the serving path.

XLA programs are shape-specialized: every distinct input shape is a
fresh trace + compile, and a compile in the request path is a latency
cliff three orders of magnitude above a dispatch.  The serving
subsystem therefore never runs a request at its natural shape — it
pads up to the nearest rung of a small, finite ladder of shapes, each
of which has an AOT-compiled program (see predictor.py).  This is the
CUDA-graph-bucket idea of the "Hybrid JIT-CUDA Graph Optimization for
Low-Latency LLM Inference" paper applied at the XLA level: capture a
handful of programs once, route every request through one of them.

Two padding dimensions:

* **batch** — rung ladder, default powers of two (``1,2,4,...,32``);
  a request of n rows runs at the smallest rung >= n, extra rows are
  zero-padding that the caller trims off (mask-off semantics);
* **sequence-style axes** — any non-batch axis can carry a round-up
  rule (``seq_axes={1: 64}``: axis 1 rounds up to the next multiple
  of 64), bounding the program count for variable-length inputs.

The ladder is deliberately dumb and explicit: ``batch_for(n)`` and
``pad_shape(shape)`` are pure functions of the configuration, so the
set of programs a model can ever compile is enumerable up front —
that is what makes one-compile-per-bucket assertable in CI
(ci/serve_smoke.py).
"""

from __future__ import annotations

__all__ = ["BucketLadder", "ServeError", "OverloadError",
           "DeadlineExceededError", "RequestCancelled"]


class ServeError(RuntimeError):
    """Typed failure of the serving subsystem (bad shapes, closed
    batchers, unknown models)."""


class OverloadError(ServeError):
    """Admission rejected: the batcher queue is at its request-count
    or byte cap (``MXNET_SERVE_MAX_QUEUE`` / ``_BYTES``).  Shedding at
    submit time is deliberate — an unbounded queue turns overload into
    OOM and every queued caller's tail latency into the backlog's."""


class DeadlineExceededError(ServeError):
    """The request's deadline passed before it was dispatched.  The
    dispatcher sheds expired requests *before* padding/dispatch, so an
    expired row never rides through XLA."""


class RequestCancelled(ServeError):
    """The caller abandoned the request (:meth:`ServeFuture.cancel`)
    and its queue slot was reclaimed before dispatch."""


#: default batch rungs: powers of two through 32
DEFAULT_BATCHES = (1, 2, 4, 8, 16, 32)

#: hard cap on one rung (a tuned store proposing a 10^6-row program
#: is a corrupt store, not a configuration)
MAX_BATCH_RUNG = 4096

#: hard cap on the rung COUNT — the ladder's whole point is a small
#: finite program set; past this the warm cost stops being a load-time
#: detail
MAX_RUNGS = 64


class BucketLadder:
    """The finite set of padded shapes the serving path may run at.

    Parameters
    ----------
    batches : sequence of int
        Batch rungs — ANY strictly ascending list of positive ints,
        not just powers of two (tuned ladders from the autotune
        ``TuningStore`` are arbitrary rung lists; bit-equality at
        non-power-of-two rungs is proven in tests/test_autotune.py).
        Validated strictly ascending (a duplicate or out-of-order
        rung is a store/config typo worth failing loudly on) and
        capped at :data:`MAX_BATCH_RUNG` per rung /
        :data:`MAX_RUNGS` rungs.  A request of n rows maps to the
        smallest rung >= n; n larger than the top rung is the
        caller's problem (the batcher splits, direct callers get a
        :class:`ServeError`).
    seq_axes : dict axis -> multiple, optional
        Non-batch axes rounded UP to the next multiple.  Axis numbers
        are into the full input shape (batch is axis 0, so the first
        sequence-ish axis is 1).
    seq_max : dict axis -> cap, optional
        Hard upper bound per rounded axis — a longer input raises
        instead of compiling an unplanned program.
    """

    def __init__(self, batches=DEFAULT_BATCHES, seq_axes=None,
                 seq_max=None):
        rungs = [int(b) for b in batches]
        if not rungs or rungs[0] < 1:
            raise ServeError("bucket ladder needs positive batch rungs, "
                             "got %r" % (batches,))
        for lo, hi in zip(rungs, rungs[1:]):
            if hi <= lo:
                raise ServeError(
                    "bucket ladder rungs must be strictly ascending "
                    "(got %r — a duplicate or out-of-order rung is a "
                    "config typo, not an ordering preference)"
                    % (list(batches),))
        if rungs[-1] > MAX_BATCH_RUNG:
            raise ServeError(
                "bucket ladder rung %d exceeds the %d cap — each rung "
                "is one AOT program at that batch size"
                % (rungs[-1], MAX_BATCH_RUNG))
        if len(rungs) > MAX_RUNGS:
            raise ServeError(
                "bucket ladder has %d rungs, over the %d cap — the "
                "ladder must stay a small finite program set"
                % (len(rungs), MAX_RUNGS))
        self.batches = tuple(rungs)
        self.seq_axes = {int(a): int(m)
                         for a, m in (seq_axes or {}).items()}
        for a, m in self.seq_axes.items():
            if a == 0 or m < 1:
                raise ServeError(
                    "seq_axes rounds non-batch axes up to a positive "
                    "multiple (got axis %d multiple %d)" % (a, m))
        self.seq_max = {int(a): int(m) for a, m in (seq_max or {}).items()}

    @property
    def max_batch(self):
        return self.batches[-1]

    def batch_for(self, n):
        """Smallest batch rung >= *n*."""
        n = int(n)
        if n < 1:
            raise ServeError("batch size must be >= 1, got %d" % n)
        for b in self.batches:
            if b >= n:
                return b
        raise ServeError(
            "request batch %d exceeds the ladder's top rung %d — split "
            "the request or extend the ladder" % (n, self.max_batch))

    def round_axis(self, axis, size):
        """*size* rounded up per this ladder's rule for *axis* (identity
        when the axis carries no rule)."""
        mult = self.seq_axes.get(int(axis))
        if mult is None:
            return int(size)
        rounded = ((int(size) + mult - 1) // mult) * mult
        cap = self.seq_max.get(int(axis))
        if cap is not None and rounded > cap:
            raise ServeError(
                "axis %d size %d rounds to %d, over the ladder cap %d"
                % (axis, size, rounded, cap))
        return rounded

    def pad_shape(self, shape):
        """The bucketed (padded) full shape for a natural input
        *shape*: batch to its rung, rounded axes up to their multiple,
        everything else unchanged."""
        shape = tuple(int(s) for s in shape)
        if not shape:
            return shape
        out = [self.batch_for(shape[0])]
        for ax in range(1, len(shape)):
            out.append(self.round_axis(ax, shape[ax]))
        return tuple(out)

    def bucket_key(self, shapes):
        """Canonical hashable key for a {name: padded_shape} dict —
        what the predictor's program cache is keyed on."""
        return tuple(sorted((n, tuple(s)) for n, s in shapes.items()))

    def __repr__(self):
        extra = ""
        if self.seq_axes:
            extra = ", seq_axes=%r" % (self.seq_axes,)
        return "BucketLadder(batches=%r%s)" % (list(self.batches), extra)
