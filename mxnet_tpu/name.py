"""Symbol name management (reference: python/mxnet/name.py —
NameManager auto-naming + the Prefix scope).

The machinery itself lives in ``symbol/symbol.py`` (``_NameManager``,
which auto-numbers anonymous symbols); this module is the public API
surface: ``with mx.name.Prefix('layer1_'):`` prepends a prefix to every
auto-generated name created in scope.
"""

from __future__ import annotations

from .symbol.symbol import _NameManager

__all__ = ["NameManager", "Prefix", "current"]


class NameManager:
    """Context manager installing a fresh name counter scope."""

    def __enter__(self):
        self._saved = getattr(_NameManager._tls, "inst", None)
        _NameManager._tls.inst = _NameManager()
        return _NameManager._tls.inst

    def __exit__(self, *exc):
        if self._saved is None:
            del _NameManager._tls.inst
        else:
            _NameManager._tls.inst = self._saved
        return False


class Prefix(NameManager):
    """Prefix every auto-generated symbol name in scope
    (reference: name.py Prefix)."""

    def __init__(self, prefix):
        self._prefix = prefix

    def __enter__(self):
        mgr = super().__enter__()
        prefix = self._prefix
        base_fresh = mgr.fresh

        def fresh(hint):
            return prefix + base_fresh(hint)
        mgr.fresh = fresh
        return mgr


def current():
    """The active name manager (reference: NameManager.current)."""
    return _NameManager.get()
