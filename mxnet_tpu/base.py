"""Shared base utilities: dtype tables, registry helper, errors.

Counterpart of the reference's ``python/mxnet/base.py`` (ctypes plumbing,
op-module codegen at base.py:578).  Here there is no C ABI between the Python
front end and the op registry — ops are registered in-process (see
``mxnet_tpu/ops/registry.py``) and surfaced into the ``nd``/``sym`` namespaces
by ``mxnet_tpu/ndarray/register.py`` / ``mxnet_tpu/symbol/register.py``.
"""

from __future__ import annotations

import numpy as _np

__all__ = [
    "MXNetError", "string_types", "numeric_types", "integer_types",
    "DTYPE_NAMES", "np_dtype", "dtype_name", "registry",
]


class MXNetError(RuntimeError):
    """Framework error type (reference: base.py MXNetError)."""


string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)

# dtype name <-> numpy dtype. bfloat16 is first-class on TPU (the reference's
# float16 configs map to bfloat16 here; float16 is still accepted).
import ml_dtypes as _ml_dtypes

bfloat16 = _np.dtype(_ml_dtypes.bfloat16)

_DTYPE_MAP = {
    "float32": _np.dtype(_np.float32),
    "float64": _np.dtype(_np.float64),
    "float16": _np.dtype(_np.float16),
    "bfloat16": bfloat16,
    "uint8": _np.dtype(_np.uint8),
    "int8": _np.dtype(_np.int8),
    "int32": _np.dtype(_np.int32),
    "int64": _np.dtype(_np.int64),
    "bool": _np.dtype(_np.bool_),
}
DTYPE_NAMES = tuple(_DTYPE_MAP)


def np_dtype(dtype):
    """Normalize a dtype-ish (str/np.dtype/type/None) to a numpy dtype."""
    if dtype is None:
        return _np.dtype(_np.float32)
    if isinstance(dtype, str):
        if dtype in _DTYPE_MAP:
            return _DTYPE_MAP[dtype]
        return _np.dtype(dtype)
    return _np.dtype(dtype)


def dtype_name(dtype):
    dt = np_dtype(dtype)
    if dt == bfloat16:
        return "bfloat16"
    return dt.name


class _Registry:
    """Tiny name->object registry with alias support.

    Plays the role of dmlc registry macros (DMLC_REGISTRY_*) used throughout
    the reference for ops, optimizers, initializers, iterators and metrics.
    """

    def __init__(self, kind):
        self.kind = kind
        self._map = {}

    def register(self, obj=None, name=None, aliases=()):
        def _do(o):
            key = name or getattr(o, "__name__", None)
            if key is None:
                raise ValueError("cannot infer registry name")
            self._map[key.lower()] = o
            for a in aliases:
                self._map[a.lower()] = o
            return o
        if obj is None:
            return _do
        return _do(obj)

    def get(self, name):
        try:
            return self._map[name.lower()]
        except KeyError:
            raise KeyError("%s %r is not registered; known: %s" %
                           (self.kind, name, sorted(self._map)))

    def __contains__(self, name):
        return name.lower() in self._map

    def keys(self):
        return self._map.keys()


_registries = {}


def registry(kind):
    """Get-or-create the registry for *kind* ('optimizer', 'metric', ...)."""
    if kind not in _registries:
        _registries[kind] = _Registry(kind)
    return _registries[kind]
