"""Network visualization (reference: python/mxnet/visualization.py —
print_summary + plot_network).

``print_summary`` walks the symbol graph and prints a layer table with
output shapes and parameter counts.  ``plot_network`` renders a graphviz
Digraph when the ``graphviz`` package is installed (it is optional, as in
the reference)."""

from __future__ import annotations

import json

__all__ = ["print_summary", "plot_network"]


def _param_names(nodes):
    """Names of weight/bias variable nodes (op == null, not data/label)."""
    out = set()
    for node in nodes:
        name = node["name"]
        if node["op"] == "null" and not name.endswith(("data", "label")) \
                and name != "data":
            out.add(name)
    return out


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Print a per-layer summary table (reference: visualization.py
    print_summary)."""
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    head_ids = {h[0] for h in conf["heads"]}
    params = _param_names(nodes)
    shapes_by_name = {}
    if shape is not None:
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**shape)
        for name, shp in zip(symbol.list_arguments(), arg_shapes):
            shapes_by_name[name] = shp
        for name, shp in zip(symbol.list_auxiliary_states(), aux_shapes):
            shapes_by_name[name] = shp
        internals = symbol.get_internals()
        _, int_shapes, _ = internals.infer_shape(**shape)
        for name, shp in zip(internals.list_outputs(), int_shapes):
            if shp is not None:  # vars come back None; keep arg shapes
                shapes_by_name[name] = shp

    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #",
              "Previous Layer"]

    def print_row(vals):
        line = ""
        for v, p in zip(vals, positions):
            line = (line + str(v))[:p - 1].ljust(p)
        print(line)

    print("=" * line_length)
    print_row(fields)
    print("=" * line_length)
    total_params = 0
    for node_id, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null" and node_id not in head_ids:
            continue
        inputs = [nodes[i[0]]["name"] for i in node.get("inputs", [])]
        cnt = 0
        for pname in inputs:
            if pname in params and pname in shapes_by_name:
                n = 1
                for s in shapes_by_name[pname]:
                    n *= s
                cnt += n
        total_params += cnt
        out_name = name + "_output" if op != "null" else name
        out_shape = shapes_by_name.get(
            out_name, shapes_by_name.get(name, ""))
        prev = ",".join(n for n in inputs if n not in params)
        print_row(["%s (%s)" % (name, op), out_shape, cnt, prev])
    print("=" * line_length)
    print("Total params: %d" % total_params)
    print("=" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a graphviz Digraph of the symbol (reference:
    visualization.py plot_network).  Requires the optional ``graphviz``
    package."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError(
            "plot_network requires the optional 'graphviz' package "
            "(the reference has the same optional dependency)")
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    params = _param_names(nodes)
    dot = Digraph(name=title, format=save_format)
    attrs = {"shape": "box", "fixedsize": "false"}
    attrs.update(node_attrs or {})
    drawn = set()
    for node in nodes:
        op = node["op"]
        name = node["name"]
        if op == "null":
            if hide_weights and name in params:
                continue
            dot.node(name=name, label=name,
                     **dict(attrs, fillcolor="#8dd3c7", style="filled"))
        else:
            dot.node(name=name, label="%s\n%s" % (name, op),
                     **dict(attrs, fillcolor="#fb8072", style="filled"))
        drawn.add(name)
    for node in nodes:
        if node["op"] == "null":
            continue
        for inp in node.get("inputs", []):
            src = nodes[inp[0]]["name"]
            if src in drawn:
                dot.edge(src, node["name"])
    return dot
