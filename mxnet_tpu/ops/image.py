"""Device-side image ops (reference capability: src/operator/image/ —
to_tensor, normalize, flip, color jitter family).

These are the in-graph counterparts of mx.image's host augmenters: they
run on device as part of the compiled program (e.g. normalize fused into
the first conv by XLA), for pipelines that ship uint8 batches to HBM and
do the float conversion there — the bandwidth-optimal TPU layout.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("_image_to_tensor", aliases=("to_tensor",))
def _to_tensor(data):
    """HWC (or NHWC) uint8 [0,255] -> CHW (NCHW) float32 [0,1]."""
    x = data.astype(jnp.float32) / 255.0
    if x.ndim == 3:
        return jnp.transpose(x, (2, 0, 1))
    return jnp.transpose(x, (0, 3, 1, 2))


@register_op("_image_normalize", aliases=("image_normalize",))
def _normalize(data, mean=(0.0,), std=(1.0,)):
    """Channel-wise normalize CHW/NCHW float input."""
    mean = jnp.asarray(mean, data.dtype)
    std = jnp.asarray(std, data.dtype)
    shape = (-1, 1, 1) if data.ndim == 3 else (1, -1, 1, 1)
    return (data - mean.reshape(shape)) / std.reshape(shape)


@register_op("_image_flip_left_right", aliases=("flip_left_right",))
def _flip_lr(data):
    return jnp.flip(data, axis=-1)


@register_op("_image_flip_top_bottom", aliases=("flip_top_bottom",))
def _flip_tb(data):
    return jnp.flip(data, axis=-2)


@register_op("_image_random_flip_left_right", needs_rng=True,
             aliases=("random_flip_left_right",))
def _random_flip_lr(rng, data, p=0.5):
    flip = jax.random.bernoulli(rng, p)
    return jnp.where(flip, jnp.flip(data, axis=-1), data)


@register_op("_image_random_flip_top_bottom", needs_rng=True,
             aliases=("random_flip_top_bottom",))
def _random_flip_tb(rng, data, p=0.5):
    flip = jax.random.bernoulli(rng, p)
    return jnp.where(flip, jnp.flip(data, axis=-2), data)


@register_op("_image_random_brightness", needs_rng=True,
             aliases=("random_brightness",))
def _random_brightness(rng, data, min_factor=0.5, max_factor=1.5):
    alpha = jax.random.uniform(rng, (), minval=min_factor,
                               maxval=max_factor)
    return data * alpha


@register_op("_image_random_contrast", needs_rng=True,
             aliases=("random_contrast",))
def _random_contrast(rng, data, min_factor=0.5, max_factor=1.5):
    alpha = jax.random.uniform(rng, (), minval=min_factor,
                               maxval=max_factor)
    coef = jnp.asarray([0.299, 0.587, 0.114], data.dtype)
    axis = 0 if data.ndim == 3 else 1
    gray = jnp.mean(
        jnp.tensordot(coef, jnp.moveaxis(data, axis, 0), axes=1))
    return data * alpha + gray * (1.0 - alpha)


@register_op("_image_random_saturation", needs_rng=True,
             aliases=("random_saturation",))
def _random_saturation(rng, data, min_factor=0.5, max_factor=1.5):
    alpha = jax.random.uniform(rng, (), minval=min_factor,
                               maxval=max_factor)
    coef = jnp.asarray([0.299, 0.587, 0.114], data.dtype)
    axis = 0 if data.ndim == 3 else 1
    gray = jnp.tensordot(coef, jnp.moveaxis(data, axis, 0), axes=1)
    gray = jnp.expand_dims(gray, axis)
    return data * alpha + gray * (1.0 - alpha)
