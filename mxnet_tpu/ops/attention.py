"""Scaled-dot-product attention: the long-context stance of this framework.

The reference predates Transformers — its only artifact is
``_contrib_div_sqrt_dim`` (reference: src/operator/contrib/transformer.cc:33)
and sequence scaling comes from bucketing + the fused RNN op (SURVEY §5.7).
On TPU the idiomatic equivalent is one attention op with a flash (blockwise,
online-softmax) kernel, plus a sequence-parallel ring variant over the ICI
mesh (``mxnet_tpu.parallel.sequence``).  This module provides:

- ``_chunked_attention``: lax.scan blockwise attention with online softmax —
  O(S * chunk) activation memory, differentiable through the scan, runs on
  every backend.  This is also the recompute path for the flash backward.
- ``flash_attention``: Pallas TPU forward kernel (MXU-tiled, VMEM-resident
  blocks, online softmax in f32 scratch) with a custom VJP whose backward
  recomputes via the chunked path.
- ``_contrib_DotProductAttention`` / ``_contrib_div_sqrt_dim`` registered
  operators, so the op is reachable from mx.nd / mx.sym like any other.

Layout is (batch, heads, seq, head_dim) throughout.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .registry import register_op

__all__ = ["flash_attention", "attention_reference"]

_NEG_INF = -1e30


def attention_reference(q, k, v, causal=False, sm_scale=None):
    """O(S^2)-memory einsum attention — the numeric oracle for tests."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        qlen, klen = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), bool), klen - qlen)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


# ---------------------------------------------------------------------------
# Chunked (blockwise) attention: scan over K/V chunks with online softmax.
# ---------------------------------------------------------------------------

def _online_softmax_update(o, m, l, s, vb):
    """One blockwise online-softmax accumulation step over masked scores
    *s* against value block *vb*; shared by the chunked scan here and the
    ring-attention scan (parallel/sequence.py) so the two paths cannot
    drift numerically."""
    m_new = jnp.maximum(m, s.max(axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l = l * alpha + p.sum(axis=-1)
    o = o * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, vb.astype(jnp.float32))
    return o, m_new, l

def _chunked_attention(q, k, v, causal=False, sm_scale=None, chunk=512):
    """Blockwise attention with online softmax over K chunks.

    Memory is O(S_q * chunk) instead of O(S_q * S_k); the scan body is
    rematerialized on backward (jax.checkpoint), which is exactly the
    flash-attention recompute strategy expressed at the XLA level.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    b, h, sq, d = q.shape
    sk = k.shape[2]
    chunk = min(chunk, sk)
    nchunk = -(-sk // chunk)
    pad = nchunk * chunk - sk
    if pad:
        kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    else:
        kp, vp = k, v
    kc = kp.reshape(b, h, nchunk, chunk, d).transpose(2, 0, 1, 3, 4)
    vc = vp.reshape(b, h, nchunk, chunk, d).transpose(2, 0, 1, 3, 4)
    qf = q.astype(jnp.float32)
    q_pos = jnp.arange(sq) + (sk - sq)  # align ends for causal cross-length

    @jax.checkpoint
    def body(carry, xs):
        o, m, l = carry
        ci, kb, vb = xs
        s = jnp.einsum("bhqd,bhkd->bhqk", qf,
                       kb.astype(jnp.float32)) * sm_scale
        k_pos = ci * chunk + jnp.arange(chunk)
        valid = k_pos < sk
        if causal:
            valid = valid[None, :] & (k_pos[None, :] <= q_pos[:, None])
            s = jnp.where(valid[None, None], s, _NEG_INF)
        else:
            s = jnp.where(valid[None, None, None, :], s, _NEG_INF)
        o, m, l = _online_softmax_update(o, m, l, s, vb)
        return (o, m, l), None

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    (o, m, l), _ = jax.lax.scan(
        body, (o0, m0, l0), (jnp.arange(nchunk), kc, vc))
    return (o / l[..., None]).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash forward kernel.
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref,
                      acc_ref, m_ref, l_ref, *, sm_scale, causal,
                      blk_q, blk_k, seq_q, seq_k):
    ik = pl.program_id(2)
    nk = pl.num_programs(2)
    iq = pl.program_id(1)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _compute():
        q = q_ref[0].astype(jnp.float32)           # (blk_q, d)
        k = k_ref[0].astype(jnp.float32)           # (blk_k, d)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32
                                ) * sm_scale

        k_pos = ik * blk_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = k_pos < seq_k
        if causal:
            # sequence ends aligned (decode-style cross-length causal),
            # same convention as attention_reference/_chunked_attention
            q_pos = (iq * blk_q + (seq_k - seq_q)
                     + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0))
            mask = mask & (k_pos <= q_pos)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_ref[...] = l_ref[...] * alpha + p.sum(axis=-1)
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # skip K blocks entirely above the diagonal: their tiles are fully
        # masked and would pay two MXU dots for nothing (~2x on sq == sk)
        visible = ik * blk_k <= iq * blk_q + blk_q - 1 + (seq_k - seq_q)
        pl.when(visible)(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _finish():
        o_ref[0] = (acc_ref[...] / l_ref[...][:, None]).astype(o_ref.dtype)


def _flash_fwd_pallas(q, k, v, causal, sm_scale, blk_q=1024, blk_k=1024,
                      interpret=False):
    """Flash forward: grid (B*H, nq, nk); f32 accumulators in VMEM scratch."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    blk_q = min(blk_q, sq)
    blk_k = min(blk_k, sk)
    # pad seq dims to block multiples, head dim to the 128-lane tile
    d_pad = -d % 128
    sq_pad = -sq % blk_q
    sk_pad = -sk % blk_k
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, sq_pad), (0, d_pad)))
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, sk_pad), (0, d_pad)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, sk_pad), (0, d_pad)))
    bh = b * h
    dp = d + d_pad
    qp = qp.reshape(bh, sq + sq_pad, dp)
    kp = kp.reshape(bh, sk + sk_pad, dp)
    vp = vp.reshape(bh, sk + sk_pad, dp)
    nq = (sq + sq_pad) // blk_q
    nk = (sk + sk_pad) // blk_k

    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
        blk_q=blk_q, blk_k=blk_k, seq_q=sq, seq_k=sk)
    out = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, blk_q, dp), lambda bh_, iq, ik: (bh_, iq, 0)),
            pl.BlockSpec((1, blk_k, dp), lambda bh_, iq, ik: (bh_, ik, 0)),
            pl.BlockSpec((1, blk_k, dp), lambda bh_, iq, ik: (bh_, ik, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, dp),
                               lambda bh_, iq, ik: (bh_, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq + sq_pad, dp), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((blk_q, dp), jnp.float32),
            pltpu.VMEM((blk_q,), jnp.float32),
            pltpu.VMEM((blk_q,), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp)
    return out.reshape(b, h, sq + sq_pad, dp)[:, :, :sq, :d]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, sm_scale, interpret):
    return _flash_fwd_pallas(q, k, v, causal, sm_scale, interpret=interpret)


def _flash_vjp_fwd(q, k, v, causal, sm_scale, interpret):
    return _flash(q, k, v, causal, sm_scale, interpret), (q, k, v)


def _flash_vjp_bwd(causal, sm_scale, interpret, res, g):
    # flash backward = recompute; the chunked scan (itself rematerialized)
    # is that recompute expressed at the XLA level.
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _chunked_attention(q_, k_, v_, causal, sm_scale),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal=False, sm_scale=None, interpret=False):
    """Blockwise (flash) attention, (B, H, S, D) layout.

    Pallas MXU kernel on TPU; chunked-scan XLA path elsewhere.  Both have
    O(S * block) activation memory; grads flow through either.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret or jax.default_backend() == "tpu":
        return _flash(q, k, v, causal, float(sm_scale), interpret)
    return _chunked_attention(q, k, v, causal, sm_scale)


# ---------------------------------------------------------------------------
# Operator registrations.
# ---------------------------------------------------------------------------

@register_op("_contrib_DotProductAttention",
             input_names=("query", "key", "value"))
def _dot_product_attention(query, key, value, causal=False, sm_scale=None,
                           chunk=512):
    """Fused scaled-dot-product attention (TPU-native; no reference
    counterpart — the reference predates Transformers, SURVEY §5.7)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(query.shape[-1])
    if jax.default_backend() == "tpu":
        return _flash(query, key, value, bool(causal), float(sm_scale), False)
    return _chunked_attention(query, key, value, bool(causal),
                              float(sm_scale), int(chunk))
