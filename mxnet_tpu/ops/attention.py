"""Scaled-dot-product attention: the long-context stance of this framework.

The reference predates Transformers — its only artifact is
``_contrib_div_sqrt_dim`` (reference: src/operator/contrib/transformer.cc:33)
and sequence scaling comes from bucketing + the fused RNN op (SURVEY §5.7).
On TPU the idiomatic equivalent is one attention op with a flash (blockwise,
online-softmax) kernel, plus a sequence-parallel ring variant over the ICI
mesh (``mxnet_tpu.parallel.sequence``).  This module provides:

- ``_chunked_attention``: lax.scan blockwise attention with online softmax —
  O(S * chunk) activation memory, differentiable through the scan, runs on
  every backend (the non-TPU dispatch target).
- ``flash_attention``: Pallas TPU kernels — MXU-tiled forward with online
  softmax in f32 scratch (saving the per-row logsumexp), and a custom VJP
  running the standard flash backward as two Pallas kernels
  (``_flash_bwd_dkdv_kernel`` / ``_flash_bwd_dq_kernel``) that recompute
  p from the saved logsumexp and accumulate blockwise.
- ``_contrib_DotProductAttention`` / ``_contrib_div_sqrt_dim`` registered
  operators, so the op is reachable from mx.nd / mx.sym like any other.

Layout is (batch, heads, seq, head_dim) throughout.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the TPU compiler-params dataclass was renamed across jax releases
# (TPUCompilerParams -> CompilerParams); accept either spelling
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    getattr(pltpu, "TPUCompilerParams")

from ._precision import matmul_precision
from .registry import register_op

__all__ = ["flash_attention", "attention_reference"]

_NEG_INF = -1e30


def attention_reference(q, k, v, causal=False, sm_scale=None):
    """O(S^2)-memory einsum attention — the numeric oracle for tests.

    Degenerate-row convention (shared by all paths in this module): a
    causal query row that can see NO keys (seq_q > seq_k under the
    aligned-ends convention) outputs zeros and contributes zero
    gradient — softmax over an empty visible set is undefined, and both
    the uniform-average and NaN alternatives leak masked content or
    poison training."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   precision=matmul_precision(q.dtype, k.dtype)) \
        * sm_scale
    if causal:
        qlen, klen = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), bool), klen - qlen)
        s = jnp.where(mask, s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        p = p * mask.any(-1)[:, None]  # zero fully-masked rows
    else:
        p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                      precision=matmul_precision(q.dtype, v.dtype)
                      ).astype(q.dtype)


# ---------------------------------------------------------------------------
# Chunked (blockwise) attention: scan over K/V chunks with online softmax.
# ---------------------------------------------------------------------------

def _online_softmax_update(o, m, l, s, vb):
    """One blockwise online-softmax accumulation step over masked scores
    *s* against value block *vb*; shared by the chunked scan here and the
    ring-attention scan (parallel/sequence.py) so the two paths cannot
    drift numerically.

    p is cast to vb's storage dtype for the MXU dot (full bf16 rate;
    f32 inputs are untouched) while the o/m/l state stays f32 via
    preferred_element_type — the same convention as the Pallas kernel."""
    m_new = jnp.maximum(m, s.max(axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l = l * alpha + p.sum(axis=-1)
    o = o * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(vb.dtype), vb,
        precision=matmul_precision(vb.dtype, vb.dtype),
        preferred_element_type=jnp.float32)
    return o, m_new, l


def _finalize_softmax(o, m, l):
    """Final division of an online-softmax accumulation, applying the
    degenerate-row convention: rows whose running max *m* never rose
    above the _NEG_INF sentinel saw no visible key and output zeros
    (with zero gradient — l_safe keeps the untaken 0/0 branch out of
    the vjp, where 0 * nan would poison it).  Shared by the chunked and
    ring paths; the flash kernel encodes the same rule in-kernel."""
    degenerate = m <= _NEG_INF * 0.5
    l_safe = jnp.where(degenerate, 1.0, l)
    return jnp.where(degenerate[..., None], 0.0, o / l_safe[..., None])

def _chunked_attention(q, k, v, causal=False, sm_scale=None, chunk=512):
    """Blockwise attention with online softmax over K chunks.

    Memory is O(S_q * chunk) instead of O(S_q * S_k); the scan body is
    rematerialized on backward (jax.checkpoint), which is exactly the
    flash-attention recompute strategy expressed at the XLA level.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    b, h, sq, d = q.shape
    sk = k.shape[2]
    chunk = min(chunk, sk)
    nchunk = -(-sk // chunk)
    pad = nchunk * chunk - sk
    if pad:
        kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    else:
        kp, vp = k, v
    kc = kp.reshape(b, h, nchunk, chunk, d).transpose(2, 0, 1, 3, 4)
    vc = vp.reshape(b, h, nchunk, chunk, d).transpose(2, 0, 1, 3, 4)
    q_pos = jnp.arange(sq) + (sk - sq)  # align ends for causal cross-length

    @jax.checkpoint
    def body(carry, xs):
        o, m, l = carry
        ci, kb, vb = xs
        # storage-dtype operands, f32 accumulation: bf16 runs at the
        # full MXU rate (a pre-cast to f32 would halve it)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kb,
                       precision=matmul_precision(q.dtype, kb.dtype),
                       preferred_element_type=jnp.float32) * sm_scale
        k_pos = ci * chunk + jnp.arange(chunk)
        valid = k_pos < sk
        if causal:
            valid = valid[None, :] & (k_pos[None, :] <= q_pos[:, None])
            s = jnp.where(valid[None, None], s, _NEG_INF)
        else:
            s = jnp.where(valid[None, None, None, :], s, _NEG_INF)
        o, m, l = _online_softmax_update(o, m, l, s, vb)
        return (o, m, l), None

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    (o, m, l), _ = jax.lax.scan(
        body, (o0, m0, l0), (jnp.arange(nchunk), kc, vc))
    return _finalize_softmax(o, m, l).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash forward kernel.
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *maybe_lse_and_scratch,
                      sm_scale, causal, blk_q, blk_k, seq_q, seq_k):
    if len(maybe_lse_and_scratch) == 4:
        lse_ref, acc_ref, m_ref, l_ref = maybe_lse_and_scratch
    else:  # inference path: no logsumexp output allocated
        lse_ref = None
        acc_ref, m_ref, l_ref = maybe_lse_and_scratch
    ik = pl.program_id(2)
    nk = pl.num_programs(2)
    iq = pl.program_id(1)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _compute():
        # operands stay in their storage dtype: a bf16 x bf16 MXU dot
        # with f32 accumulation (preferred_element_type) runs at the
        # full bf16 MXU rate — pre-casting to f32 would halve it
        q = q_ref[0]                               # (blk_q, d)
        k = k_ref[0]                               # (blk_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32
                                ) * sm_scale

        k_pos = ik * blk_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = k_pos < seq_k
        if causal:
            # sequence ends aligned (decode-style cross-length causal),
            # same convention as attention_reference/_chunked_attention
            q_pos = (iq * blk_q + (seq_k - seq_q)
                     + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0))
            mask = mask & (k_pos <= q_pos)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, s.max(axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_ref[...] = l_ref[...] * alpha + p.sum(axis=-1)
        m_ref[...] = m_new
        # p in v's dtype for the second MXU dot (flash convention: the
        # f32 online-softmax state carries the precision; p's entries
        # are probabilities in [0,1] where bf16 relative error is ~2^-8)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # skip K blocks entirely above the diagonal: their tiles are fully
        # masked and would pay two MXU dots for nothing (~2x on sq == sk)
        visible = ik * blk_k <= iq * blk_q + blk_q - 1 + (seq_k - seq_q)
        pl.when(visible)(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _finish():
        # rows whose running max never rose above the sentinel saw no
        # visible key (causal with seq_q > seq_k): emit zeros, and a
        # +1e30 lse so the backward's recomputed p = exp(s - lse)
        # underflows to 0 for them — zero output, zero gradient, same
        # convention as attention_reference/_chunked_attention
        m = m_ref[...]
        l = l_ref[...]
        # Mosaic cannot widen an i1 vector to 2D; reshape the f32 state
        # first and build the mask at its final rank instead
        deg2 = m[:, None] <= _NEG_INF * 0.5
        l_safe2 = jnp.where(deg2, 1.0, l[:, None])
        o_ref[0] = jnp.where(deg2, 0.0,
                             acc_ref[...] / l_safe2).astype(o_ref.dtype)
        if lse_ref is not None:
            # logsumexp residual for the flash backward
            degenerate = m <= _NEG_INF * 0.5
            l_safe = jnp.where(degenerate, 1.0, l)
            lse_ref[0] = jnp.where(degenerate, -_NEG_INF,
                                   m + jnp.log(l_safe))


def _pad_bh(x, s_pad, d_pad):
    b, h, s, d = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (0, s_pad), (0, d_pad)))
    return xp.reshape(b * h, s + s_pad, d + d_pad)


def _flash_fwd_pallas(q, k, v, causal, sm_scale, blk_q=1024, blk_k=1024,
                      interpret=False, with_lse=False):
    """Flash forward: grid (B*H, nq, nk); f32 accumulators in VMEM
    scratch.  ``with_lse`` also returns the per-row logsumexp residual
    (the flash backward's recompute anchor)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    blk_q = min(blk_q, sq)
    blk_k = min(blk_k, sk)
    # pad seq dims to block multiples, head dim to the 128-lane tile
    d_pad = -d % 128
    sq_pad = -sq % blk_q
    sk_pad = -sk % blk_k
    qp = _pad_bh(q, sq_pad, d_pad)
    kp = _pad_bh(k, sk_pad, d_pad)
    vp = _pad_bh(v, sk_pad, d_pad)
    bh = b * h
    dp = d + d_pad
    nq = (sq + sq_pad) // blk_q
    nk = (sk + sk_pad) // blk_k

    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
        blk_q=blk_q, blk_k=blk_k, seq_q=sq, seq_k=sk)
    out_specs = [pl.BlockSpec((1, blk_q, dp),
                              lambda bh_, iq, ik: (bh_, iq, 0))]
    out_shape = [jax.ShapeDtypeStruct((bh, sq + sq_pad, dp), q.dtype)]
    if with_lse:  # training: also emit the logsumexp residual
        out_specs.append(pl.BlockSpec((1, blk_q),
                                      lambda bh_, iq, ik: (bh_, iq)))
        out_shape.append(jax.ShapeDtypeStruct((bh, sq + sq_pad),
                                              jnp.float32))
    res = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, blk_q, dp), lambda bh_, iq, ik: (bh_, iq, 0)),
            pl.BlockSpec((1, blk_k, dp), lambda bh_, iq, ik: (bh_, ik, 0)),
            pl.BlockSpec((1, blk_k, dp), lambda bh_, iq, ik: (bh_, ik, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((blk_q, dp), jnp.float32),
            pltpu.VMEM((blk_q,), jnp.float32),
            pltpu.VMEM((blk_q,), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp)
    out = res[0].reshape(b, h, sq + sq_pad, dp)[:, :, :sq, :d]
    if with_lse:
        return out, res[1]  # lse stays padded (bh, sqp) for the bwd
    return out


# ---------------------------------------------------------------------------
# Pallas flash backward kernels (standard flash-attention backward:
# recompute p from the saved logsumexp, accumulate dq / dk / dv blockwise;
# delta_i = rowsum(dO_i * O_i) precomputed at the XLA level).
# ---------------------------------------------------------------------------

def _bwd_p_block(q_ref, k_ref, lse_ref, iq, ik, *, sm_scale, causal,
                 blk_q, blk_k, seq_q, seq_k):
    """Recomputed softmax block p = exp(q k^T * scale - lse).

    The dot keeps the storage dtype (bf16 runs at full MXU rate) and
    accumulates f32 via preferred_element_type."""
    q = q_ref[0]
    k = k_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
    k_pos = ik * blk_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    mask = k_pos < seq_k
    if causal:
        q_pos = (iq * blk_q + (seq_k - seq_q)
                 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0))
        mask = mask & (k_pos <= q_pos)
    s = jnp.where(mask, s, _NEG_INF)
    return jnp.exp(s - lse_ref[0][:, None])


def _flash_bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                           delta_ref, dk_ref, dv_ref,
                           dk_acc, dv_acc, *, sm_scale, causal,
                           blk_q, blk_k, seq_q, seq_k):
    ik = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _compute():
        p = _bwd_p_block(q_ref, k_ref, lse_ref, iq, ik,
                         sm_scale=sm_scale, causal=causal, blk_q=blk_q,
                         blk_k=blk_k, seq_q=seq_q, seq_k=seq_k)
        do = do_ref[0]
        v = v_ref[0]
        q = q_ref[0]
        # dv += p^T dO — p cast to the storage dtype for a full-rate
        # MXU dot; accumulators stay f32
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # ds = p * (dO v^T - delta) * scale;  dk += ds^T q
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, None]) * sm_scale
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        visible = ik * blk_k <= iq * blk_q + blk_q - 1 + (seq_k - seq_q)
        pl.when(visible)(_compute)
    else:
        _compute()

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                         delta_ref, dq_ref, dq_acc, *, sm_scale, causal,
                         blk_q, blk_k, seq_q, seq_k):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _compute():
        p = _bwd_p_block(q_ref, k_ref, lse_ref, iq, ik,
                         sm_scale=sm_scale, causal=causal, blk_q=blk_q,
                         blk_k=blk_k, seq_q=seq_q, seq_k=seq_k)
        do = do_ref[0]
        v = v_ref[0]
        k = k_ref[0]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, None]) * sm_scale
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        visible = ik * blk_k <= iq * blk_q + blk_q - 1 + (seq_k - seq_q)
        pl.when(visible)(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, dout, causal, sm_scale,
                      blk_q=1024, blk_k=1024, interpret=False):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    blk_q = min(blk_q, sq)
    blk_k = min(blk_k, sk)
    d_pad = -d % 128
    sq_pad = -sq % blk_q
    sk_pad = -sk % blk_k
    qp = _pad_bh(q, sq_pad, d_pad)
    kp = _pad_bh(k, sk_pad, d_pad)
    vp = _pad_bh(v, sk_pad, d_pad)
    dop = _pad_bh(dout, sq_pad, d_pad)
    outp = _pad_bh(out, sq_pad, d_pad)
    bh, dp = b * h, d + d_pad
    nq = (sq + sq_pad) // blk_q
    nk = (sk + sk_pad) // blk_k
    # delta_i = rowsum(dO_i * O_i) — zero on padded rows since dO is 0
    delta = jnp.sum(dop.astype(jnp.float32) * outp.astype(jnp.float32),
                    axis=-1)

    common = dict(sm_scale=sm_scale, causal=causal, blk_q=blk_q,
                  blk_k=blk_k, seq_q=sq, seq_k=sk)
    q_spec_q = pl.BlockSpec((1, blk_q, dp), lambda bh_, a, b_: (bh_, a, 0))
    q_spec_k = pl.BlockSpec((1, blk_q, dp), lambda bh_, a, b_: (bh_, b_, 0))
    k_spec_q = pl.BlockSpec((1, blk_k, dp), lambda bh_, a, b_: (bh_, b_, 0))
    k_spec_k = pl.BlockSpec((1, blk_k, dp), lambda bh_, a, b_: (bh_, a, 0))
    r_spec_q = pl.BlockSpec((1, blk_q), lambda bh_, a, b_: (bh_, a))
    r_spec_k = pl.BlockSpec((1, blk_q), lambda bh_, a, b_: (bh_, b_))

    # dk/dv: grid (bh, nk, nq) — k-block resident, q streamed
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkdv_kernel, **common),
        grid=(bh, nk, nq),
        in_specs=[q_spec_k, k_spec_k, k_spec_k, q_spec_k, r_spec_k,
                  r_spec_k],
        out_specs=[k_spec_k, k_spec_k],
        out_shape=[jax.ShapeDtypeStruct((bh, sk + sk_pad, dp), k.dtype),
                   jax.ShapeDtypeStruct((bh, sk + sk_pad, dp), v.dtype)],
        scratch_shapes=[pltpu.VMEM((blk_k, dp), jnp.float32),
                        pltpu.VMEM((blk_k, dp), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lse, delta)

    # dq: grid (bh, nq, nk) — q-block resident, k streamed
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, **common),
        grid=(bh, nq, nk),
        in_specs=[q_spec_q, k_spec_q, k_spec_q, q_spec_q, r_spec_q,
                  r_spec_q],
        out_specs=q_spec_q,
        out_shape=jax.ShapeDtypeStruct((bh, sq + sq_pad, dp), q.dtype),
        scratch_shapes=[pltpu.VMEM((blk_q, dp), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lse, delta)

    dq = dq.reshape(b, h, sq + sq_pad, dp)[:, :, :sq, :d]
    dk = dk.reshape(b, h, sk + sk_pad, dp)[:, :, :sk, :d]
    dv = dv.reshape(b, h, sk + sk_pad, dp)[:, :, :sk, :d]
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, sm_scale, interpret):
    return _flash_fwd_pallas(q, k, v, causal, sm_scale, interpret=interpret)


def _flash_vjp_fwd(q, k, v, causal, sm_scale, interpret):
    out, lse = _flash_fwd_pallas(q, k, v, causal, sm_scale,
                                 interpret=interpret, with_lse=True)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, sm_scale, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_bwd_pallas(q, k, v, out, lse, g, causal, sm_scale,
                             interpret=interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal=False, sm_scale=None, interpret=False,
                    chunk=512):
    """Blockwise (flash) attention, (B, H, S, D) layout.

    Pallas MXU kernel on TPU; chunked-scan XLA path elsewhere (*chunk*
    is its block length).  Both have O(S * block) activation memory;
    grads flow through either.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret:
        dt = jnp.result_type(q.dtype, k.dtype, v.dtype)
        return _flash(q.astype(dt), k.astype(dt), v.astype(dt),
                      causal, float(sm_scale), True).astype(q.dtype)

    def _tpu(q, k, v):
        # the kernels' MXU dots need one operand dtype (f32 q against a
        # bf16 KV cache would raise); promote once here so the uniform
        # bf16 fast path is untouched.  NOTE platform_dependent traces
        # BOTH branches on every platform (lax.cond), so the promotion
        # must stay inside the branch
        dt = jnp.result_type(q.dtype, k.dtype, v.dtype)
        return _flash(q.astype(dt), k.astype(dt), v.astype(dt),
                      causal, float(sm_scale), False).astype(q.dtype)

    def _other(q, k, v):
        return _chunked_attention(q, k, v, causal, sm_scale,
                                  int(chunk)).astype(q.dtype)

    # decided at LOWERING time per platform (not by the process-default
    # backend, which is wrong in a mixed cpu+tpu session).  On this
    # jax release platform_dependent still LOWERS every branch for the
    # target platform, and the Mosaic pallas_call has no CPU lowering
    # rule at all — so in a process with no TPU devices (where the tpu
    # branch could never be taken anyway) skip straight to the XLA
    # chunked path instead of tripping "Only interpret mode is
    # supported on CPU backend" at compile time.
    try:
        have_tpu = any(d.platform == "tpu" for d in jax.devices())
    except Exception:
        have_tpu = False
    if not have_tpu:
        return _other(q, k, v)
    return jax.lax.platform_dependent(q, k, v, tpu=_tpu, default=_other)


# ---------------------------------------------------------------------------
# Operator registrations.
# ---------------------------------------------------------------------------

@register_op("_contrib_DotProductAttention",
             input_names=("query", "key", "value"))
def _dot_product_attention(query, key, value, causal=False, sm_scale=None,
                           chunk=512):
    """Fused scaled-dot-product attention (TPU-native; no reference
    counterpart — the reference predates Transformers, SURVEY §5.7)."""
    return flash_attention(query, key, value, causal=bool(causal),
                           sm_scale=sm_scale, chunk=chunk)
